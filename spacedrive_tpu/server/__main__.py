"""CLI entry: the headless server shell (apps/server/src/main.rs).

Env parity: DATA_DIR and PORT are honored like the reference (main.rs:15-33);
SD_AUTH=user:password enables basic auth; SD_INIT_DATA points at a debug
fixture file (util/debug_initializer.rs:79).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="spacedrive_tpu.server")
    parser.add_argument("--data-dir",
                        default=os.environ.get("DATA_DIR", "./sd_data"))
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=int(os.environ.get("PORT", "8080")))
    parser.add_argument("--auth", default=os.environ.get("SD_AUTH"),
                        help="user:password for basic auth")
    parser.add_argument("--log-level", default=os.environ.get("SD_LOG", "INFO"))
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname).1s %(name)s %(message)s")

    from ..node import Node
    from .shell import Server

    node = Node(args.data_dir)
    server = Server(node, args.host, args.port, auth=args.auth)
    server.start()
    # announce the bound port on stdout so drivers/tests can parse it
    print(f"LISTENING {server.host}:{server.port}", flush=True)

    stop = {"flag": False}

    def on_signal(_sig, _frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop["flag"]:
            signal.pause()
    except KeyboardInterrupt:
        pass
    server.stop()
    node.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
