"""Multi-process reader pool: rspc query dispatch off the node's GIL.

ISSUE 11 tentpole. PR 10's instruments proved one Python process cannot
serve heavy read traffic while indexing — during a live 20k-file scan
the read path collapsed to 9 req/s with multi-second p99 tails, and the
slow-request span trees attributed the tail to reader-lock wait plus
the scan's GIL/commit pressure. SQLite WAL already permits multi-process
readers and the sdlint ``query-discipline`` pass guarantees query
handlers are read-only, so the process boundary is enforceable: this
module forks N worker processes, each holding its OWN read-only SQLite
connection per library (``Database(readonly=True)`` — the per-process
reader bootstrap in models/base), and routes pool-marked queries
(``@router.query(..., pool=True)``, statically vetted by the sdlint
``worker-purity`` pass) to them. Writes, mutations, jobs, sync and
subscriptions never leave the node process.

Topology (docs/architecture/serving.md):

- **Dispatch**: ``Router.resolve`` hands a pool-marked query to
  :meth:`ReaderPool.dispatch`; one worker serves one request at a time
  (checkout from an idle list), replies are pickled over a pipe. Any
  pool failure raises :class:`PoolUnavailable` and the router re-runs
  the query in-process — the degradation ladder pool → in-process is
  always safe because queries are read-only.
- **Invalidation**: the pool keeps a per-library integer *watermark*
  bumped by a synchronous event-bus hook on every data-changing event
  (``db.commit`` from the pipeline group committer and the CRDT-ingest
  session, ``invalidate_query`` from mutations, ``sync_message``).
  Every dispatch carries the current watermark; a worker's
  hot-directory-page LRU entry only hits when its stored watermark
  equals the request's, and each SELECT on the read-only connection
  starts a fresh WAL read transaction — so a read dispatched after a
  commit at watermark W can never return pre-W rows.
- **Supervision**: a supervisor thread health-checks idle workers every
  ``SD_SERVE_HEALTH_S`` (the ping doubles as the watermark/stats sync),
  reaps and respawns dead ones, and a dispatcher that finds its worker
  dead (or unresponsive past ``SD_SERVE_REQUEST_TIMEOUT_S``) retires it
  and fails the in-flight request over to the in-process path.

``SD_SERVE_WORKERS=0`` disables the pool entirely (the degraded mode
``bench.py --serve`` A/Bs against); unset defaults to
``min(4, cpu_count)``.
"""

from __future__ import annotations

import json
import logging
import multiprocessing
import os
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path
from typing import TYPE_CHECKING, Any

from .. import faults, telemetry
from ..telemetry.registry import REQUEST_BUCKETS, estimate_quantiles
# knob parses that can never take the pool down (malformed → default);
# hoisted to utils so the search engine shares one implementation
from ..utils import env_float as _env_float
from ..utils import env_int as _env_int
from ..utils.locks import SdLock

if TYPE_CHECKING:
    from ..node import Node

logger = logging.getLogger(__name__)

#: event kinds that mean "committed rows changed" for a library — the
#: watermark bump set. Conservative by design: over-bumping only costs
#: cache hits, under-bumping would serve stale pages.
BUMP_KINDS = frozenset({"db.commit", "invalidate_query", "sync.newMessage",
                        "job_progress"})

#: event kinds that mean "the DB FILE was swapped" (backup restore, repair)
#: — a watermark bump is not enough: a worker's open read-only connection
#: still points at the old inode, so these advance the library's reader
#: EPOCH and every worker closes + reopens before its next read
RELOAD_KINDS = frozenset({"library.reload"})

# module handles only — the families (and their help text, the single
# copy) are declared in telemetry._declare_core, which ran when the
# telemetry package imported above; these are get-or-create lookups
_REQUESTS = telemetry.counter("sd_serve_worker_requests_total",
                              labels=("worker", "outcome"))
_SECONDS = telemetry.histogram("sd_serve_worker_request_seconds",
                               labels=("worker",), buckets=REQUEST_BUCKETS)
_CACHE = telemetry.counter("sd_serve_worker_cache_total",
                           labels=("worker", "result"))
_RESTARTS = telemetry.counter("sd_serve_worker_restarts_total",
                              labels=("worker", "reason"))
_LIVE = telemetry.gauge("sd_serve_workers")
_INVALIDATIONS = telemetry.counter("sd_serve_invalidations_total")
_QUEUE_WAIT = telemetry.histogram("sd_serve_queue_wait_seconds",
                                  buckets=REQUEST_BUCKETS)
_RESIZES = telemetry.counter("sd_serve_pool_resizes_total",
                             labels=("direction",))


class PoolUnavailable(Exception):
    """The pool could not serve this dispatch (not running, disabled,
    saturated, or the worker died mid-request) — the router falls back
    to the in-process path, which is always safe for read-only queries."""


def configured_workers() -> int:
    """``SD_SERVE_WORKERS`` (0 disables the pool); defaults to
    ``min(4, cpu_count)``."""
    raw = os.environ.get("SD_SERVE_WORKERS", "").strip()
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return min(4, os.cpu_count() or 1)




# ---------------------------------------------------------------------------
# worker side (runs in the forked child)
# ---------------------------------------------------------------------------


class _PageCache:
    """Watermark-keyed LRU over query responses. An entry hits only when
    its stored watermark equals the request's current one for that
    library, so invalidation is a watermark bump — no explicit delete
    races with reads."""

    def __init__(self, capacity: int) -> None:
        self.capacity = max(0, capacity)
        self._entries: OrderedDict[tuple, tuple[int, Any]] = OrderedDict()
        self._watermarks: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def sync(self, watermarks: dict[str, int]) -> None:
        """Fold the node's watermark map in and drop stale entries (the
        between-requests eviction; the per-request check is authoritative)."""
        for lib, wm in watermarks.items():
            if wm > self._watermarks.get(lib, 0):
                self._watermarks[lib] = wm
        stale = [k for k, (wm, _r) in self._entries.items()
                 if wm != self._watermarks.get(k[0], 0)]
        for k in stale:
            del self._entries[k]

    def lookup(self, lib: str, proc: str, arg: Any, wm: int):
        """(hit, key, result): the key is reused for :meth:`store`."""
        if wm > self._watermarks.get(lib, 0):
            self._watermarks[lib] = wm
        try:
            key = (lib, proc, json.dumps(arg, sort_keys=True, default=str))
        except (TypeError, ValueError):
            return False, None, None
        entry = self._entries.get(key)
        if entry is not None and entry[0] == wm:
            self._entries.move_to_end(key)
            return True, key, entry[1]
        if entry is not None and wm > entry[0]:
            del self._entries[key]  # genuinely stale entry
        elif entry is not None:
            # straggler: this REQUEST is older than the cached page (its
            # watermark was read before a bump) — serve it fresh from
            # SQLite but neither evict nor overwrite the newer entry
            return False, None, None
        return False, key, None

    def drop_library(self, lib: str) -> None:
        """Epoch change: every cached page of this library is void."""
        for key in [k for k in self._entries if k[0] == lib]:
            del self._entries[key]

    def store(self, key: tuple | None, wm: int, result: Any) -> None:
        if key is None or self.capacity == 0:
            return
        self._entries[key] = (wm, result)
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)


class _ReaderLibrary:
    """What a pool-pure handler may touch of a Library: ``id`` and a
    read-only ``db``. No sync manager, no node backref — the worker-
    purity pass keeps handlers inside this surface."""

    __slots__ = ("id", "db")

    def __init__(self, lib_id: str, db: Any) -> None:
        self.id = lib_id
        self.db = db


class _ReaderLibraries:
    """Per-process reader bootstrap: opens ``libraries/<id>.db`` with a
    read-only connection on first use. Lazy, so libraries created after
    the fork are visible; a vanished file surfaces as the same 404 the
    router raises for an unloaded library."""

    def __init__(self, libraries_dir: Path) -> None:
        self.dir = libraries_dir
        self._open: dict[str, _ReaderLibrary] = {}
        self._epochs: dict[str, int] = {}

    def get(self, lib_id: str, epoch: int = 0) -> _ReaderLibrary:
        import sqlite3

        from ..api.router import ApiError
        from ..models import ALL_MODELS, Database

        if epoch > self._epochs.get(lib_id, 0):
            # the node swapped the DB file (restore/repair): the open
            # connection points at the old inode — close and reopen
            self._epochs[lib_id] = epoch
            stale = self._open.pop(lib_id, None)
            if stale is not None:
                try:
                    stale.db.close()
                except Exception:
                    pass
        lib = self._open.get(lib_id)
        if lib is not None:
            return lib
        # the id becomes a filename — same hygiene as the trace exports
        if not lib_id or any(c in lib_id for c in "/\\") or ".." in lib_id \
                or len(lib_id) > 64:
            raise ApiError(f"library {lib_id!r} not loaded", code=404)
        path = self.dir / f"{lib_id}.db"
        if not path.is_file():
            raise ApiError(f"library {lib_id!r} not loaded", code=404)
        try:
            db = Database(path, ALL_MODELS, readonly=True)
        except sqlite3.Error as e:
            raise ApiError(f"library {lib_id!r} unreadable: {e}",
                           code=404) from None
        lib = _ReaderLibrary(lib_id, db)
        self._open[lib_id] = lib
        return lib


class _ReaderNode:
    """The node surrogate handlers see inside a worker: libraries +
    data_dir and nothing else. A handler reaching for node-held mutable
    state (jobs, sync, p2p, events) gets an AttributeError — which the
    worker reports and the dispatcher fails over; the sdlint
    ``worker-purity`` pass makes that unreachable for marked handlers."""

    def __init__(self, data_dir: Path) -> None:
        self.data_dir = Path(data_dir)
        self.libraries = _ReaderLibraries(self.data_dir / "libraries")
        self.reader_pool = None  # a worker never nests a pool
        # the device search engine lives in the NODE process only (the
        # router bypasses the pool for engine-served dispatches); inside
        # a worker the search handlers see None and serve plain SQL
        self.search_engine = None


def _serve_one(runtime_node, router, cache: _PageCache, msg: dict) -> dict:
    from ..api.router import QUERY, ApiError

    key = msg.get("proc", "")
    arg = msg.get("arg")
    library_id = msg.get("library_id")
    wm = int(msg.get("wm") or 0)
    epoch = int(msg.get("epoch") or 0)
    try:
        # chaos seam: `serve_worker:kill` is the worker-death drill the
        # crash harness arms (the plan is inherited across the fork)
        faults.inject("serve_worker", key=key)
        # callers may name an EXTRA seam for this dispatch (the replica
        # tier passes `replica_serve` so its chaos kinds land inside the
        # worker actually serving the remote query, not the node process)
        extra_seam = msg.get("seam")
        if extra_seam:
            faults.inject(str(extra_seam), key=key)
        proc = router.procedures.get(key)
        if proc is None or proc.kind != QUERY or not proc.pool:
            raise ApiError(f"{key} is not pool-dispatchable")
        if epoch > runtime_node.libraries._epochs.get(library_id or "", 0):
            cache.drop_library(library_id or "")
        hit, cache_key, cached = cache.lookup(
            library_id or "", key, arg, wm)
        if hit:
            return {"ok": True, "raw": cached, "hit": True}
        if proc.scope == "library":
            result = proc.fn(
                runtime_node,
                runtime_node.libraries.get(library_id, epoch=epoch), arg)
        else:
            result = proc.fn(runtime_node, arg)
        # serialize ONCE, in the worker: the same encoder Response.json
        # uses, so the shell can splice these bytes into the HTTP
        # envelope verbatim — the node process neither decodes nor
        # re-encodes the page, and cache hits replay the encoded bytes
        encoded = json.dumps(result, default=str).encode()
        cache.store(cache_key, wm, encoded)
        return {"ok": True, "raw": encoded, "hit": False}
    except ApiError as e:
        return {"ok": False, "api": True, "error": str(e), "code": e.code}
    except Exception as e:  # 500-class, exactly like an in-process crash
        return {"ok": False, "api": False,
                "error": f"{type(e).__name__}: {e}"}


def _worker_main(conn, data_dir: str, slot: int) -> None:
    """Forked worker loop: requests and control messages over one pipe.
    First move is disabling telemetry — the child registry is invisible
    to /metrics, and skipping it sidesteps any lock a fork could have
    caught mid-increment. Per-request stats travel back in the reply and
    are folded into the node-process ``sd_serve_worker_*`` families."""
    from .. import telemetry as _telemetry
    from ..api.router import mount as api_mount

    _telemetry.set_enabled(False)
    node = _ReaderNode(Path(data_dir))
    router = api_mount(node)
    cache = _PageCache(_env_int("SD_SERVE_CACHE", 256))
    served = 0
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError, KeyboardInterrupt):
            break
        except Exception:
            # a garbled frame means the parent-side state is unknowable;
            # exit and let the supervisor respawn a clean process
            break
        if not isinstance(msg, dict):
            continue
        ctl = msg.get("ctl")
        if ctl == "shutdown":
            break
        if ctl == "sync":
            cache.sync(msg.get("watermarks") or {})
            reply: dict[str, Any] = {"ok": True, "pong": True,
                                     "served": served,
                                     "cache_entries": len(cache)}
        else:
            reply = _serve_one(node, router, cache, msg)
            served += 1
        try:
            conn.send(reply)
        except (BrokenPipeError, OSError):
            break
        except Exception as e:  # unpicklable result — report, don't die
            try:
                conn.send({"ok": False, "api": False,
                           "error": f"unpicklable response: {e}"})
            except Exception:
                break


# ---------------------------------------------------------------------------
# node side
# ---------------------------------------------------------------------------


class _Worker:
    __slots__ = ("slot", "proc", "conn", "generation", "dead")

    def __init__(self, slot: int, proc, conn, generation: int) -> None:
        self.slot = slot
        self.proc = proc
        self.conn = conn
        self.generation = generation
        self.dead = False


class ReaderPool:
    def __init__(self, node: "Node", workers: int | None = None) -> None:
        self.node = node
        self.workers = configured_workers() if workers is None else workers
        self._ctx = multiprocessing.get_context("fork")
        self._slots: list[_Worker | None] = [None] * self.workers
        self._idle: list[_Worker] = []
        # FIFO checkout tickets: bounded by the number of concurrently
        # dispatching threads (each waiter holds exactly one ticket)
        self._tickets: deque[object] = deque()
        self._cv = threading.Condition()
        self._wm_lock = SdLock("serve.pool.watermarks")
        self._watermarks: dict[str, int] = {}
        self._epochs: dict[str, int] = {}
        self._enabled = True
        self._cache_hits = 0
        self._cache_misses = 0
        self._running = False
        self._generation = 0
        self._restarts = 0
        self._failovers = 0
        self._worker_stats: dict[int, dict] = {}
        self._respawn_wake = threading.Event()
        self._supervisor: threading.Thread | None = None
        self.health_s = _env_float("SD_SERVE_HEALTH_S", 1.0)
        self.request_timeout_s = _env_float("SD_SERVE_REQUEST_TIMEOUT_S",
                                            30.0)
        self.queue_wait_s = _env_float("SD_SERVE_QUEUE_WAIT_S", 2.0)
        # autosizer (ISSUE 20): resize between SD_SERVE_WORKERS_MIN/MAX
        # driven by the windowed queue-wait p95 the checkouts record.
        # Both default to the configured worker count, so the pool stays
        # fixed-size unless an operator opens a range.
        self.min_workers = max(1, _env_int("SD_SERVE_WORKERS_MIN",
                                           self.workers))
        self.max_workers = max(self.min_workers,
                               _env_int("SD_SERVE_WORKERS_MAX",
                                        self.workers))
        self.workers = min(max(self.workers, self.min_workers),
                           self.max_workers)
        self._slots = [None] * self.workers
        self.autosize_cooldown_s = _env_float("SD_SERVE_AUTOSIZE_COOLDOWN_S",
                                              max(2.0, 2 * self.health_s))
        self.grow_wait_s = _env_float("SD_SERVE_GROW_WAIT_S", 0.05)
        self.shrink_wait_s = _env_float("SD_SERVE_SHRINK_WAIT_S", 0.005)
        #: previous queue-wait bucket snapshot (windowed p95, the
        #: _P99_PREV pattern from telemetry/requests.py)
        self._qw_prev: list[int] | None = None
        self._last_resize = time.monotonic()
        self._resizes = 0

    @classmethod
    def maybe_start(cls, node: "Node") -> "ReaderPool | None":
        """The shell's entry point: None when ``SD_SERVE_WORKERS=0``
        keeps the node in the degraded in-process mode."""
        n = configured_workers()
        if n <= 0:
            return None
        return cls(node, workers=n).start()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReaderPool":
        if self.workers <= 0:
            raise ValueError("ReaderPool needs at least one worker")
        self._running = True
        try:
            for slot in range(self.workers):
                self._spawn(slot)
        except BaseException:
            # partial boot (fork/pipe failure mid-loop): tear down the
            # slots already spawned — the caller never gets a pool handle,
            # so nothing else could ever stop them
            self.stop()
            raise
        self.node.events.on(self._on_event)
        self._supervisor = threading.Thread(
            target=self._supervise, name="sd-serve-supervisor", daemon=True)
        self._supervisor.start()
        logger.info("reader pool started: %d workers", self.workers)
        return self

    def stop(self) -> None:
        self._running = False
        self._respawn_wake.set()
        try:
            self.node.events.off(self._on_event)
        except Exception:
            pass
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        with self._cv:
            workers = [w for w in self._slots if w is not None]
            # a worker NOT in the idle list is checked out by a dispatch
            # thread that may be mid-send/recv on its pipe right now —
            # multiprocessing.Connection is not thread-safe, so those get
            # a kill (the dispatcher sees EOF and fails over) instead of
            # a second writer interleaving frames on the same conn
            idle = set(self._idle)
            self._slots = [None] * self.workers
            self._idle.clear()
            self._cv.notify_all()
        for w in workers:
            if w not in idle:
                try:
                    w.proc.kill()
                except Exception:
                    pass
                continue
            try:
                w.conn.send({"ctl": "shutdown"})
            except (BrokenPipeError, OSError):
                pass
        deadline = time.monotonic() + 2.0
        for w in workers:
            w.proc.join(timeout=max(0.0, deadline - time.monotonic()))
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(timeout=2)
            try:
                w.conn.close()
            except OSError:
                pass
        _LIVE.set(0.0)

    def set_enabled(self, value: bool) -> None:
        """Runtime bypass (the serve bench's pool-vs-in-process A/B):
        disabled, every dispatch raises PoolUnavailable and the router
        serves in-process; workers stay warm."""
        self._enabled = bool(value)
        with self._cv:
            self._cv.notify_all()  # parked checkouts re-check the gate

    # -- invalidation --------------------------------------------------------
    def _on_event(self, event) -> None:
        """Synchronous bus hook (runs in the committing thread, after the
        durable commit that emitted the event): bump the library's
        watermark so every LATER dispatch carries a fresher key than any
        cached pre-commit page. No pipe IO here — the hot path only pays
        a dict update; eviction rides the supervisor's next sync."""
        lib_id = getattr(event, "library_id", None)
        if not lib_id:
            return
        if event.kind in RELOAD_KINDS:
            with self._wm_lock:
                self._epochs[lib_id] = self._epochs.get(lib_id, 0) + 1
                self._watermarks[lib_id] = \
                    self._watermarks.get(lib_id, 0) + 1
            _INVALIDATIONS.inc()
            return
        if event.kind not in BUMP_KINDS:
            return
        with self._wm_lock:
            self._watermarks[lib_id] = self._watermarks.get(lib_id, 0) + 1
        _INVALIDATIONS.inc()

    def watermark(self, lib_id: str | None) -> tuple[int, int]:
        """(watermark, epoch) for a library — the freshness pair every
        dispatch carries."""
        if not lib_id:
            return 0, 0
        with self._wm_lock:
            return (self._watermarks.get(lib_id, 0),
                    self._epochs.get(lib_id, 0))

    def _count_failover(self) -> None:
        with self._wm_lock:  # int += is not atomic across threads
            self._failovers += 1

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, key: str, arg: Any, library_id: str | None,
                 seam: str | None = None) -> Any:
        """Run one pool-marked query on a worker. Raises ApiError exactly
        as the in-process handler would; raises PoolUnavailable when the
        caller should fail over in-process — including on non-Api worker
        errors, where the in-process re-run reproduces the handler's
        original exception with full fidelity.

        ``seam`` names an extra fault seam injected INSIDE the worker for
        this dispatch (the replica serve path passes ``replica_serve`` so
        a `replica_serve:kill` drill takes down the worker serving the
        remote query, never the dispatching node)."""
        if not (self._running and self._enabled):
            raise PoolUnavailable("pool not running")
        try:
            worker = self._checkout()
        except PoolUnavailable:
            # saturation/stopping spills are failovers too — an operator
            # tuning SD_SERVE_QUEUE_WAIT_S or the worker count needs them
            # visible (`worker="pool"`: no slot was ever involved)
            self._count_failover()
            _REQUESTS.inc(worker="pool", outcome="failover")
            raise
        label = str(worker.slot)
        wm, epoch = self.watermark(library_id)
        req = {"proc": key, "arg": arg, "library_id": library_id,
               "wm": wm, "epoch": epoch}
        if seam:
            req["seam"] = seam
        t0 = time.perf_counter()
        try:
            worker.conn.send(req)
            if not worker.conn.poll(self.request_timeout_s):
                raise TimeoutError(
                    f"no reply in {self.request_timeout_s:.0f}s")
            reply = worker.conn.recv()
        except TimeoutError as e:
            self._retire(worker, reason="timeout")
            self._count_failover()
            _REQUESTS.inc(worker=label, outcome="failover")
            raise PoolUnavailable(f"worker {label} wedged: {e}") from None
        except Exception as e:
            # EOF/broken pipe (worker died), but also anything else the
            # pipe can throw mid-frame (UnpicklingError on a garbled
            # stream, MemoryError on a huge reply): the connection state
            # is unknowable, so the worker must be retired either way —
            # returning nothing here would leak the checked-out slot
            # forever (the supervisor only respawns DEAD processes)
            self._retire(worker, reason="crash")
            self._count_failover()
            _REQUESTS.inc(worker=label, outcome="failover")
            raise PoolUnavailable(f"worker {label} died: {e}") from None
        if not isinstance(reply, dict):
            # protocol violation: the framing survived but the payload is
            # garbage — retire the worker and fail over
            self._retire(worker, reason="crash")
            self._count_failover()
            _REQUESTS.inc(worker=label, outcome="failover")
            raise PoolUnavailable(f"malformed worker reply: {type(reply)}")
        self._checkin(worker)
        _SECONDS.observe(time.perf_counter() - t0, worker=label)
        if "hit" in reply:
            _CACHE.inc(worker=label,
                       result="hit" if reply["hit"] else "miss")
            with self._wm_lock:  # int += is not atomic across threads
                if reply["hit"]:
                    self._cache_hits += 1
                else:
                    self._cache_misses += 1
        if reply.get("ok"):
            from ..api.router import RawJson

            _REQUESTS.inc(worker=label, outcome="ok")
            raw = reply.get("raw")
            if raw is not None:
                return RawJson(raw)
            return reply.get("result")
        if reply.get("api"):
            from ..api.router import ApiError

            _REQUESTS.inc(worker=label, outcome="api_error")
            raise ApiError(str(reply.get("error")),
                           code=int(reply.get("code") or 400))
        # non-Api handler failure: fail over to the in-process path — the
        # documented ladder. A handler that (via a helper) reached beyond
        # the worker surrogate surface serves fine in-process; a genuinely
        # broken handler re-raises its ORIGINAL exception there, with
        # better fidelity than a wrapped worker error. Queries are
        # read-only, so the re-run is always safe.
        self._count_failover()
        _REQUESTS.inc(worker=label, outcome="error")
        raise PoolUnavailable(
            f"worker handler error: {reply.get('error')}")

    def _checkout(self) -> _Worker:
        # the QUEUE wait is deliberately much shorter than the per-request
        # timeout: when every worker is busy (burst or wedge), spilling to
        # the in-process path in ~a health interval keeps tail latency
        # bounded — parking for the full 30 s request budget would invert
        # the degradation ladder under exactly the overload it exists for
        t0 = time.monotonic()
        deadline = t0 + self.queue_wait_s
        # FIFO ticketing: a bare condvar race lets late arrivals barge —
        # a freed worker goes to whichever dispatcher re-acquires the
        # lock first, and under a sustained burst an unlucky waiter can
        # lose every race until it spills at the deadline (measured as
        # the multi-tenant flood's quiet-tenant p99 collapsing to the
        # spill timeout). Tickets make the wait bound deterministic:
        # depth-ahead x service time, head of line served first.
        ticket = object()
        with self._cv:
            self._tickets.append(ticket)
            try:
                while True:
                    if not (self._running and self._enabled):
                        raise PoolUnavailable("pool stopping")
                    if self._idle and self._tickets[0] is ticket:
                        _QUEUE_WAIT.observe(time.monotonic() - t0)
                        return self._idle.pop()
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # the saturation spill records its full wait too —
                        # the autosizer's grow signal must see exactly the
                        # overload that is spilling dispatches in-process
                        _QUEUE_WAIT.observe(time.monotonic() - t0)
                        raise PoolUnavailable("pool saturated")
                    self._cv.wait(timeout=remaining)
            finally:
                try:
                    self._tickets.remove(ticket)
                except ValueError:
                    pass
                if self._idle and self._tickets:
                    # served or abandoned with capacity free: the new
                    # head may already be parked — wake the line
                    self._cv.notify_all()

    def _checkin(self, worker: _Worker) -> None:
        with self._cv:
            if worker.dead or self._slots[worker.slot] is not worker:
                return
            self._idle.append(worker)
            # notify_all, not notify: only the head ticket may take the
            # worker, and a single notify can land on a non-head waiter
            # (which re-parks), leaving the head asleep until its
            # timeout poll
            self._cv.notify_all()

    # -- supervision ---------------------------------------------------------
    def _spawn(self, slot: int) -> None:
        """Spawn a worker into ``slot``. The fork (page-table copy of a
        JAX-loaded interpreter — tens of ms) happens OUTSIDE the pool
        lock so dispatch checkouts never stall behind a respawn; only
        the slot install takes ``self._cv``."""
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=_worker_main,
            args=(child_conn, str(self.node.data_dir), slot),
            name=f"sd-serve-w{slot}", daemon=True)
        proc.start()
        child_conn.close()
        with self._cv:
            if not self._running or self._slots[slot] is not None:
                # stopped (or lost a race) while forking: discard cleanly
                installed = False
            else:
                self._generation += 1
                worker = _Worker(slot, proc, parent_conn, self._generation)
                self._slots[slot] = worker
                self._idle.append(worker)
                self._cv.notify_all()  # the head ticket must see it
                installed = True
            live = float(sum(1 for w in self._slots
                             if w is not None and w.proc.is_alive()))
        if not installed:
            try:
                proc.kill()
            except Exception:
                pass
            try:
                parent_conn.close()
            except OSError:
                pass
            return
        _LIVE.set(live)

    def _retire(self, worker: _Worker, reason: str) -> None:
        """Drop a dead/wedged worker and wake the supervisor to respawn
        its slot. Never blocks on the process — the dispatcher calling
        this has a client waiting on the failover."""
        with self._cv:
            if worker.dead:
                return
            worker.dead = True
            if self._slots[worker.slot] is worker:
                self._slots[worker.slot] = None
            if worker in self._idle:
                self._idle.remove(worker)
        with self._wm_lock:  # int += is not atomic across threads
            self._restarts += 1
        _RESTARTS.inc(worker=str(worker.slot), reason=reason)
        try:
            worker.proc.kill()
        except Exception:
            pass
        try:
            worker.conn.close()
        except OSError:
            pass
        with self._cv:
            _LIVE.set(float(sum(1 for w in self._slots
                                if w is not None and w.proc.is_alive())))
        self._respawn_wake.set()

    def _supervise(self) -> None:
        """Every ``health_s``: respawn empty slots, reap silently-dead
        idle workers, and ping the rest (the ping carries the watermark
        map for cache eviction and returns worker stats)."""
        while self._running:
            self._respawn_wake.wait(timeout=self.health_s)
            self._respawn_wake.clear()
            if not self._running:
                return
            empty: list[int] = []
            with self._cv:
                for slot in range(self.workers):
                    w = self._slots[slot]
                    if w is not None and not w.proc.is_alive():
                        # died while idle (SIGKILL drill, OOM): no
                        # dispatcher saw it — reap here
                        w.dead = True
                        if w in self._idle:
                            self._idle.remove(w)
                        self._slots[slot] = None
                        with self._wm_lock:
                            self._restarts += 1
                        _RESTARTS.inc(worker=str(slot), reason="crash")
                        w = None
                    if w is None:
                        empty.append(slot)
            for slot in empty:
                if not self._running:
                    break
                try:
                    self._spawn(slot)  # forks outside the pool lock
                except Exception as e:
                    # transient fork/pipe failure (EAGAIN under pid or
                    # memory pressure): the supervisor must survive it —
                    # the slot stays empty and the next tick retries
                    logger.warning("worker %d respawn failed: %s", slot, e)
                    break
            self._ping_idle_workers()
            try:
                self._autosize()
            except Exception:
                # a resize must never take the supervisor down with it
                logger.exception("pool autosize failed")

    def _autosize(self) -> None:
        """One autosizer decision per supervisor tick (ISSUE 20): grow
        when the windowed queue-wait p95 says dispatches are parking
        behind busy workers, shrink when the pool is comfortably idle.
        Inactive unless an operator opened a SD_SERVE_WORKERS_MIN/MAX
        range — both default to the configured count."""
        if self.max_workers <= self.min_workers or not self._running:
            return
        now = time.monotonic()
        if now - self._last_resize < self.autosize_cooldown_s:
            return
        counts = None
        for _labels, series in _QUEUE_WAIT.series_items():
            counts, _total, _n = series.read()
            break
        if counts is None:
            return
        prev = self._qw_prev or [0] * len(counts)
        window = [c - p for c, p in zip(counts, prev)]
        self._qw_prev = counts
        if sum(window) > 0:
            p95 = estimate_quantiles(_QUEUE_WAIT.buckets, window,
                                     qs=(0.95,))[0.95]
        else:
            # no checkouts at all since the last tick: the strongest
            # possible shrink signal, not a missing one
            p95 = 0.0
        if p95 > self.grow_wait_s and self.workers < self.max_workers:
            self._resize("grow", p95)
        elif p95 < self.shrink_wait_s and self.workers > self.min_workers:
            self._resize("shrink", p95)

    def _resize(self, direction: str, p95: float) -> None:
        if direction == "grow":
            with self._cv:
                if not self._running or self.workers >= self.max_workers:
                    return
                slot = self.workers
                self._slots.append(None)
                self.workers += 1
            try:
                self._spawn(slot)  # forks outside the pool lock
            except Exception as e:
                # slot stays empty; the supervisor's respawn sweep retries
                logger.warning("grown worker %d spawn failed: %s", slot, e)
        else:
            with self._cv:
                if self.workers <= self.min_workers:
                    return
                slot = self.workers - 1
                w = self._slots[slot]
                if w is None or w not in self._idle:
                    # only an IDLE top slot may be removed — a checked-out
                    # worker's dispatcher indexes _slots by slot number,
                    # so the list may never shrink under it. Busy top
                    # slot: try again next tick.
                    return
                self._idle.remove(w)
                self._slots.pop()
                self.workers -= 1
                w.dead = True
                _LIVE.set(float(sum(1 for x in self._slots
                                    if x is not None and x.proc.is_alive())))
            try:
                w.conn.send({"ctl": "shutdown"})
            except (BrokenPipeError, OSError):
                pass
            try:
                w.conn.close()
            except OSError:
                pass
        with self._wm_lock:  # int += is not atomic across threads
            self._resizes += 1
        self._last_resize = time.monotonic()
        _RESIZES.inc(direction=direction)
        telemetry.event("pool.resize", direction=direction,
                        workers=self.workers,
                        queue_wait_p95_ms=round(p95 * 1000.0, 2),
                        min=self.min_workers, max=self.max_workers)
        logger.info("pool %s -> %d workers (queue-wait p95 %.1f ms)",
                    direction, self.workers, p95 * 1000.0)

    def _ping_idle_workers(self) -> None:
        with self._wm_lock:
            watermarks = dict(self._watermarks)
        for slot in range(self.workers):
            with self._cv:
                w = self._slots[slot]
                if w is None or w not in self._idle:
                    continue  # busy or empty: the dispatcher supervises it
                self._idle.remove(w)
            try:
                w.conn.send({"ctl": "sync", "watermarks": watermarks})
                if not w.conn.poll(min(5.0, self.request_timeout_s)):
                    raise TimeoutError("ping timed out")
                pong = w.conn.recv()
                if isinstance(pong, dict) and pong.get("pong"):
                    self._worker_stats[slot] = {
                        "served": pong.get("served", 0),
                        "cache_entries": pong.get("cache_entries", 0),
                        "pid": w.proc.pid,
                    }
                self._checkin(w)
            except Exception:
                # same breadth as dispatch: ANY pipe failure (incl. a
                # garbled pong frame) retires the checked-out worker —
                # letting it escape would leak the slot AND kill the
                # supervisor thread
                self._retire(w, reason="health")

    # -- introspection -------------------------------------------------------
    def status(self) -> dict[str, Any]:
        """What ``telemetry.requestStats`` folds in as ``serve_pool``."""
        with self._cv:
            live = [w for w in self._slots if w is not None]
            alive = sum(1 for w in live if w.proc.is_alive())
            idle = len(self._idle)
        return {
            "workers": self.workers,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "alive": alive,
            "idle": idle,
            "enabled": self._enabled,
            "running": self._running,
            "restarts": self._restarts,
            "resizes": self._resizes,
            "failovers": self._failovers,
            # instance counters, NOT the process-global _CACHE family: a
            # restarted shell's fresh pool must report its own traffic,
            # not the previous pool's accumulated totals
            "cache_hits": self._cache_hits,
            "cache_misses": self._cache_misses,
            "watermarks": len(self._watermarks),
            "per_worker": dict(self._worker_stats),
        }
