"""Distributed read replicas over the sync mesh (ISSUE 19).

CRDT sync already replicates full library state to every paired peer, so
every device in the mesh is latent serving capacity — this module promotes
it. Pool-marked rspc queries (``@router.query(..., pool=True)``, the PR 11
surface, statically vetted by the sdlint ``worker-purity`` and
``replica-purity`` passes) become dispatchable over p2p to
**watermark-eligible** replicas.

The robustness contract, in dispatch order:

- **Never a stale row.** A replica may serve a query only when its applied
  per-instance HLC clock map (``SyncManager.timestamps()`` — the same
  series the ``sd_sync_peer_lag_*`` lag gauges derive from) covers the
  client's ``require`` map, re-checked on the replica per dispatch. The
  require map is the client's **authored floors**
  (:meth:`~..sync.manager.SyncManager.require_watermark` — per-publisher
  maxima over the client's own op LOG, which is written in the same
  transaction that materializes rows), NOT its raw clock map: ``clock.last``
  merges forward on every ingest, which would make the client's own entry
  uncoverable by any replica. Eligibility therefore implies the replica
  has applied every op the client has materialized — read-your-writes
  holds for the client's own committed writes by construction. A lagging
  or partitioned replica answers NOT_ELIGIBLE; it never guesses.
- **Degrade, don't wedge.** The ladder is strict:
  replica → local reader pool → in-process. :meth:`ReplicaRouter.dispatch`
  returns ``None`` on any miss (no peers, all ineligible, busy, errors)
  and the router falls through to ``ReaderPool.dispatch`` and then the
  in-process handler — both always-safe because queries are read-only.
  Every degradation is accounted in ``sd_replica_failovers_total``.
- **Ride the accept layer.** Replica-side serving admits through the
  node's :class:`~..sync.admission.IngestBudget` (same instance the CRDT
  receive path uses), so a flooded replica sheds queries with an explicit
  BUSY + ``retry_after_ms`` instead of buffering, and the p2p
  throttle/auto-ban layer applies to H_QUERY exactly as to sync frames.
- **Byte identity.** The replica encodes its reply with the one canonical
  encoder (``json.dumps(result, default=str).encode()`` — what the serve
  pool and ``Response.json`` use), so a replica-served page is spliceable
  and byte-comparable against the local path.

Chaos: replica-side dispatch runs through the ``replica_serve`` fault
seam (kinds eio/stall/wedge/kill/busy). With a local reader pool armed
the seam is injected INSIDE the worker serving the query (``seam=`` on
``ReaderPool.dispatch``), so a ``replica_serve:kill`` drill takes down
the serving process mid-query — the dispatching node observes a dead
replica, not its own death.

Peer selection follows the PR 6 BackendRouter shape: EWMA latency per
peer with hysteresis and a periodic exploration probe, plus per-peer
cooldowns (NOT_ELIGIBLE → short recheck, BUSY → the peer's own
``retry_after_ms``, transport error → exponential).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import TYPE_CHECKING, Any, Callable

from .. import faults, telemetry
from ..faults.spec import PeerBusyError
from ..sync.admission import Busy
from ..telemetry import mesh
from ..telemetry.registry import REQUEST_BUCKETS

if TYPE_CHECKING:
    from ..node import Node

logger = logging.getLogger(__name__)

# module handles — families declared in telemetry._declare_core
_DISPATCHES = telemetry.counter("sd_replica_dispatches_total",
                                labels=("peer", "outcome"))
_ELIGIBILITY = telemetry.counter("sd_replica_eligibility_rejections_total",
                                 labels=("peer",))
_FAILOVERS = telemetry.counter("sd_replica_failovers_total",
                               labels=("reason",))
_SECONDS = telemetry.histogram("sd_replica_request_seconds",
                               labels=("peer",), buckets=REQUEST_BUCKETS)
_SERVES = telemetry.counter("sd_replica_serves_total", labels=("outcome",))


def encode_reply(result: Any) -> bytes:
    """THE wire encoder for replica-served pages — the same call the
    serve-pool worker and ``Response.json`` make, so byte-identity vs the
    local path is an encoder identity, not a coincidence."""
    return json.dumps(result, default=str).encode()


def covers(have: dict[str, int], require: dict[str, int]) -> bool:
    """Watermark-eligibility rule: ``have`` (the replica's applied
    per-instance clock map) covers ``require`` (the client's) iff every
    instance the client has applied ops from is known here at >= the
    client's clock. An instance the replica has never heard of is only
    acceptable at floor 0 (it contributed nothing the client could have
    read)."""
    for pub, floor in (require or {}).items():
        if int(floor or 0) <= 0:
            continue
        if int(have.get(pub, 0) or 0) < int(floor):
            return False
    return True


# ---------------------------------------------------------------------------
# replica side: serve one remote query
# ---------------------------------------------------------------------------

def serve_query(node: "Node", payload: dict, peer: str = "") -> dict:
    """Serve one H_QUERY dispatch on this node (the replica). Returns a
    reply dict — never raises:

    - ``{"ok": True, "raw": bytes}`` — the encoded page;
    - ``{"ok": False, "kind": "not_eligible", "watermark": {...}}`` — the
      replica's applied clocks did not cover ``payload["require"]`` (the
      watermark rides back so the client can log/derive lag);
    - ``{"ok": False, "kind": "busy", "retry_after_ms": int}`` — admission
      shed (or an injected ``replica_serve:busy``);
    - ``{"ok": False, "kind": "error", "error": str}`` — anything else;
      the client falls down its ladder and, for a deterministic handler
      error, reproduces the original exception in-process.

    The local reader pool serves the query when armed (with the
    ``replica_serve`` seam injected inside the worker); a pool failure is
    reported as an error — the replica never silently re-runs a remote
    query in its own node process, so the TARGET's ladder does the
    failing over and the accounting stays in one place.
    """
    key = str(payload.get("key") or "")
    library_id = payload.get("library_id")
    arg = payload.get("arg")
    require = payload.get("require") or {}
    label = mesh.peer_label(peer)

    from ..api.router import QUERY, ApiError, RawJson

    proc = node.router.procedures.get(key)
    if proc is None or proc.kind != QUERY or not proc.pool \
            or not getattr(proc, "replica", True):
        _SERVES.inc(outcome="error")
        return {"ok": False, "kind": "error",
                "error": f"{key!r} is not replica-dispatchable"}
    try:
        library = node.libraries.get(library_id)
    except KeyError:
        # not a library we replicate — as ineligible as a lagging clock
        _SERVES.inc(outcome="not_eligible")
        return {"ok": False, "kind": "not_eligible", "watermark": {}}

    have = library.sync.timestamps()
    if not covers(have, require):
        _SERVES.inc(outcome="not_eligible")
        return {"ok": False, "kind": "not_eligible", "watermark": have}

    # accept layer: one shared budget with the CRDT receive path — a
    # flooded replica sheds queries exactly like sync windows
    verdict = node.ingest_budget.try_admit(f"query:{label}", 1, 0)
    if isinstance(verdict, Busy):
        _SERVES.inc(outcome="busy")
        return {"ok": False, "kind": "busy",
                "retry_after_ms": verdict.retry_after_ms}
    try:
        pool = getattr(node, "reader_pool", None)
        if pool is not None:
            from .pool import PoolUnavailable

            try:
                served = pool.dispatch(key, arg, library_id,
                                       seam="replica_serve")
            except PoolUnavailable as e:
                _SERVES.inc(outcome="error")
                return {"ok": False, "kind": "error",
                        "error": f"replica pool unavailable: {e}"}
            raw = (served.data if isinstance(served, RawJson)
                   else encode_reply(served))
        else:
            # in-process serve: the seam fires in THIS process — over real
            # p2p (or the crash harness) a `kill` here is the whole
            # replica node dying mid-query, the kill-matrix scenario
            faults.inject("replica_serve", key=key)
            result = proc.fn(node, library, arg)
            raw = encode_reply(result)
        _SERVES.inc(outcome="ok")
        return {"ok": True, "raw": raw}
    except PeerBusyError as e:
        _SERVES.inc(outcome="busy")
        return {"ok": False, "kind": "busy",
                "retry_after_ms": e.retry_after_ms}
    except ApiError as e:
        _SERVES.inc(outcome="error")
        return {"ok": False, "kind": "error", "error": str(e)}
    except Exception as e:
        _SERVES.inc(outcome="error")
        return {"ok": False, "kind": "error",
                "error": f"{type(e).__name__}: {e}"}
    finally:
        verdict.release()


# ---------------------------------------------------------------------------
# client side: the replica rung of the degradation ladder
# ---------------------------------------------------------------------------

#: how long a NOT_ELIGIBLE peer sits out before re-checking — short by
#: design: lag drains continuously and the eligibility signal is cheap
NOT_ELIGIBLE_COOLDOWN_S = 0.25
#: error-backoff geometry: base * 2^fails, capped
ERROR_BACKOFF_BASE_S = 0.1
ERROR_BACKOFF_MAX_S = 5.0
#: EWMA smoothing + switch hysteresis (the PR 6 BackendRouter constants)
EWMA_ALPHA = 0.3
HYSTERESIS = 1.25
#: every Nth dispatch probes a non-best peer so a recovered one can win back
EXPLORE_EVERY = 16
#: peers tried per dispatch before falling down the ladder — bounded so a
#: partition wave costs at most two timeouts, not a full mesh sweep
MAX_ATTEMPTS = 2


class _PeerState:
    __slots__ = ("ewma_s", "until", "fails")

    def __init__(self) -> None:
        self.ewma_s = 0.0       # 0 = never measured
        self.until = 0.0        # monotonic deadline the peer sits out to
        self.fails = 0


class ReplicaRouter:
    """Picks a watermark-eligible peer for a pool-marked query and
    dispatches over the mesh; returns ``None`` whenever the local ladder
    should take over.

    Transport-agnostic: ``candidates(library_id) -> [peer_id]`` and
    ``transport(peer_id, payload, nbytes) -> reply dict`` (raising
    ``ConnectionError``-family on link failure) are injected — production
    wires the p2p manager (:meth:`maybe_start`), the fleet harness wires
    wire-less in-process transports through the same net model."""

    def __init__(self, node: "Node",
                 candidates: Callable[[str], list[str]],
                 transport: Callable[[str, dict, int], dict]) -> None:
        self.node = node
        self._candidates = candidates
        self._transport = transport
        self._lock = threading.Lock()
        self._peers: dict[str, _PeerState] = {}
        self._dispatch_seq = 0
        self._clock = time.monotonic
        #: per-dispatch attempt bound — instance state so harnesses can
        #: widen it to cover a whole fleet in one ladder descent
        self.max_attempts = MAX_ATTEMPTS

    @classmethod
    def maybe_start(cls, node: "Node") -> "ReplicaRouter | None":
        """Production wiring: serve pool-marked queries from mesh peers
        that replicate the library, over the p2p H_QUERY stream. None
        when p2p is down (the ladder starts at the local pool) or when
        ``SD_REPLICAS=0`` pins all serving local."""
        import os

        if os.environ.get("SD_REPLICAS", "").strip() == "0":
            return None
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            return None

        def candidates(library_id: str) -> list[str]:
            try:
                return p2p.query_peers(library_id)
            except Exception:
                return []

        def transport(peer_id: str, payload: dict, nbytes: int) -> dict:
            return p2p.run_coro(
                p2p.request_query(peer_id, payload),
                timeout=replica_timeout_s() + 5.0)

        return cls(node, candidates, transport)

    # -- require map --------------------------------------------------------
    def _require(self, library_id: str) -> dict[str, int] | None:
        try:
            library = self.node.libraries.get(library_id)
        except KeyError:
            return None
        try:
            # authored floors, not the raw clock map: clock.last merges
            # forward on every ingest, which would make this client's own
            # entry uncoverable by any replica (see require_watermark)
            return library.sync.require_watermark()
        except Exception:
            return None

    # -- peer choice --------------------------------------------------------
    def _state(self, peer: str) -> _PeerState:
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = _PeerState()
        return st

    def _order(self, peers: list[str]) -> list[str]:
        """Available peers, best EWMA first, with hysteresis (an incumbent
        best is only displaced by a 1/HYSTERESIS-faster challenger) and a
        periodic exploration probe promoting the most stale measurement."""
        now = self._clock()
        with self._lock:
            self._dispatch_seq += 1
            explore = (self._dispatch_seq % EXPLORE_EVERY) == 0
            avail = [p for p in peers if self._state(p).until <= now]
            if not avail:
                return []

            def score(p: str) -> float:
                e = self._peers[p].ewma_s
                return e if e > 0 else 0.0  # unmeasured peers sort first

            avail.sort(key=score)
            if len(avail) > 1:
                best, runner = avail[0], avail[1]
                b, r = self._peers[best].ewma_s, self._peers[runner].ewma_s
                # hysteresis: keep the slightly-slower incumbent stable —
                # the incumbent is whichever has MORE recent wins, proxied
                # here by a lower fail count at comparable latency
                if (b > 0 and r > 0 and b * HYSTERESIS > r
                        and self._peers[runner].fails
                        < self._peers[best].fails):
                    avail[0], avail[1] = runner, best
                if explore:
                    # probe the tail so a recovered peer re-measures
                    avail.insert(0, avail.pop())
            return avail

    # -- dispatch -----------------------------------------------------------
    def dispatch(self, key: str, arg: Any, library_id: str | None) -> Any:
        """Try the replica rung for one pool-marked query. Returns a
        :class:`~..api.router.RawJson` on success, ``None`` when the
        caller should fall down the ladder (counted per reason in
        ``sd_replica_failovers_total`` whenever the rung was live for
        this library)."""
        if not library_id:
            return None
        peers = self._candidates(library_id)
        if not peers:
            return None  # rung not armed for this library: silent
        require = self._require(library_id)
        if require is None:
            return None
        order = self._order(peers)
        if not order:
            _FAILOVERS.inc(reason="no_peers")
            return None
        payload = {"library_id": library_id, "key": key, "arg": arg,
                   "require": require}
        nbytes = len(json.dumps(payload, default=str))
        last_reason = "error"
        for peer in order[:self.max_attempts]:
            label = mesh.peer_label(peer)
            st = self._state(peer)
            t0 = self._clock()
            try:
                reply = self._transport(peer, payload, nbytes)
            except PeerBusyError as e:
                with self._lock:
                    st.until = self._clock() + e.retry_after_ms / 1000.0
                _DISPATCHES.inc(peer=label, outcome="busy")
                last_reason = "busy"
                continue
            except Exception as e:
                with self._lock:
                    st.fails += 1
                    st.until = self._clock() + min(
                        ERROR_BACKOFF_BASE_S * (2 ** st.fails),
                        ERROR_BACKOFF_MAX_S)
                _DISPATCHES.inc(peer=label, outcome="error")
                logger.debug("replica %s transport failed: %s", label, e)
                last_reason = "error"
                continue
            dt = self._clock() - t0
            if not isinstance(reply, dict):
                with self._lock:
                    st.fails += 1
                    st.until = self._clock() + min(
                        ERROR_BACKOFF_BASE_S * (2 ** st.fails),
                        ERROR_BACKOFF_MAX_S)
                _DISPATCHES.inc(peer=label, outcome="error")
                last_reason = "error"
                continue
            if reply.get("ok"):
                raw = reply.get("raw")
                if not isinstance(raw, (bytes, bytearray)):
                    _DISPATCHES.inc(peer=label, outcome="error")
                    last_reason = "error"
                    continue
                with self._lock:
                    st.fails = 0
                    st.ewma_s = (dt if st.ewma_s <= 0 else
                                 EWMA_ALPHA * dt
                                 + (1 - EWMA_ALPHA) * st.ewma_s)
                _DISPATCHES.inc(peer=label, outcome="ok")
                _SECONDS.observe(dt, peer=label)
                from ..api.router import RawJson

                return RawJson(bytes(raw))
            kind = reply.get("kind")
            if kind == "not_eligible":
                with self._lock:
                    st.until = self._clock() + NOT_ELIGIBLE_COOLDOWN_S
                _DISPATCHES.inc(peer=label, outcome="not_eligible")
                _ELIGIBILITY.inc(peer=label)
                last_reason = "not_eligible"
            elif kind == "busy":
                retry_ms = int(reply.get("retry_after_ms") or 250)
                with self._lock:
                    st.until = self._clock() + retry_ms / 1000.0
                _DISPATCHES.inc(peer=label, outcome="busy")
                last_reason = "busy"
            else:
                with self._lock:
                    st.fails += 1
                    st.until = self._clock() + min(
                        ERROR_BACKOFF_BASE_S * (2 ** st.fails),
                        ERROR_BACKOFF_MAX_S)
                _DISPATCHES.inc(peer=label, outcome="error")
                last_reason = "error"
        _FAILOVERS.inc(reason=last_reason)
        return None

    # -- introspection ------------------------------------------------------
    def status(self) -> dict[str, Any]:
        now = self._clock()
        with self._lock:
            return {
                "peers": {
                    mesh.peer_label(p): {
                        "ewma_ms": round(st.ewma_s * 1000.0, 3),
                        "cooldown_s": round(max(0.0, st.until - now), 3),
                        "fails": st.fails,
                    } for p, st in self._peers.items()},
                "dispatches": self._dispatch_seq,
            }


def replica_timeout_s() -> float:
    """Per-dispatch transport budget (``SD_REPLICA_TIMEOUT_S``): kept well
    under the serve-pool request timeout so a wedged replica costs one
    bounded wait before the ladder's local rungs answer."""
    import os

    try:
        return max(0.1, float(os.environ.get("SD_REPLICA_TIMEOUT_S", "5")))
    except ValueError:
        return 5.0
