"""Headless server shell (apps/server equivalent).

`python -m spacedrive_tpu.server --data-dir DIR --port N` boots a Node and
serves /health, /rspc (HTTP + websocket JSON-RPC), /schema, and the
/spacedrive custom_uri file+thumbnail routes.
"""

from .shell import Server

__all__ = ["Server"]
