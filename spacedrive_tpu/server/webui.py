"""Embedded web explorer: the `interface/` + `apps/web` stand-in.

The reference ships a 19k-LoC React app; a TPU-host framework needs a
working window into the node more than a design system, so the shell embeds
a single-file vanilla-JS explorer (no build step, no assets pipeline —
axum's `feature = "assets"` embedded-dist analogue, apps/server main.rs).
It drives the same wire contract a full frontend would: rspc HTTP calls,
the /rspc/ws subscription socket for live job progress + invalidation, and
custom_uri thumbnails/files.
"""

INDEX_HTML = r"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>spacedrive_tpu</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<link rel="stylesheet" href="/client/ui.css">
</head>
<body>
<aside>
  <h1>spacedrive_tpu</h1>
  <h2>Library</h2>
  <select id="library"></select>
  <h2>Locations</h2>
  <div id="locations"></div>
  <h2>Search</h2>
  <input id="search" placeholder="search files… (enter)">
  <h2>Views</h2>
  <div class="loc" data-view="overview">overview</div>
  <div class="loc" data-view="duplicates">near-duplicates</div>
  <div class="loc" data-view="history">job history</div>
  <div class="loc" data-view="ephemeral">browse host path…</div>
  <div class="loc" data-view="settings">settings</div>
  <h2>Tags</h2>
  <div id="tags"></div>
  <h2>Albums</h2>
  <div id="albums"></div>
  <h2>Peers</h2>
  <div id="peers" class="meta">none discovered</div>
  <h2>Jobs</h2>
  <div id="jobs"></div>
  <div id="status">connecting…</div>
</aside>
<main>
  <div class="crumbs" id="crumbs"></div>
  <div id="content" class="grid"></div>
</main>
<script src="/client/procedures.js"></script>
<script>
const state = { library: null, location: null, dir: "/", ws: null };
const KIND_ICONS = {0:"📄",2:"📁",3:"📝",5:"🖼️",6:"🎵",7:"🎬",8:"🗜️",9:"⚙️",
                    11:"🔒",20:"💻",21:"🗃️",22:"📚",23:"🧾"};

async function rspc(key, arg, libraryId) {
  // the GENERATED client contract (client/procedures.js, from
  // spacedrive_tpu/api/codegen.py) is load-bearing: a key missing from it
  // means the UI and the schema drifted — fail here, not with a 404
  if (!window.SD_PROCEDURES)
    throw new Error("client contract not loaded — /client/procedures.js " +
                    "missing (run python -m spacedrive_tpu.api.codegen)");
  const meta = window.SD_PROCEDURES[key];
  if (!meta) throw new Error(`${key}: not in the generated client contract`);
  const lib = meta.scope === "library" ? (libraryId ?? state.library) : null;
  const r = await fetch(`/rspc/${key}`, {method:"POST",
    headers:{"content-type":"application/json"},
    body: JSON.stringify({arg: arg ?? null, library_id: lib})});
  const body = await r.json();
  if (body.error) throw new Error(`${key}: ${body.error}`);
  return body.result;
}

function el(tag, attrs = {}, text = "") {
  const n = document.createElement(tag);
  Object.assign(n, attrs);
  if (text) n.textContent = text;
  return n;
}

function showOnboarding(locationOnly = false, note = "") {
  // locationOnly: the library exists but has no locations yet (a failed or
  // skipped first location must not dead-end the flow — this card is the
  // only locations.create surface)
  const box = document.getElementById("content");
  box.className = ""; box.innerHTML = "";
  document.getElementById("crumbs").textContent = "welcome";
  const card = el("div", {className: "onboard"});
  card.append(el("h3", {}, locationOnly ? "Add a location"
                                        : "Create your first library"));
  const name = el("input", {placeholder: "library name", value: "My Library"});
  const path = el("input", {placeholder: locationOnly
    ? "absolute path to index" : "absolute path to index (optional)"});
  const go = el("button", {}, locationOnly ? "add location" : "create library");
  const err = el("div", {className: "kv"}, note);
  go.onclick = async () => {
    if (go.disabled || (!locationOnly && !name.value)) return;
    go.disabled = true;  // a double-click must not create two libraries
    let locErr = "";
    try {
      if (!locationOnly) {
        try {
          const lib = await rspc("libraries.create", {name: name.value}, null);
          state.library = lib.id;
        } catch (e) {  // ONLY a failed create re-enables create mode — any
          err.textContent = String(e.message || e);  // later failure must
          go.disabled = false;                       // not duplicate the
          return;                                    // library on retry
        }
      }
      if (path.value) {
        try {
          await rspc("locations.create", {path: path.value});
        } catch (e) {
          locErr = `location failed: ${e.message}`;
        }
      }
      const locs = await rspc("locations.list");
      if (!locs.length) {
        showOnboarding(true, locErr || "now add a location to index");
        return;
      }
      await loadLibraries(true);
    } catch (e) {
      showOnboarding(true, `${locErr} ${e.message || e}`.trim());
    }
  };
  if (!locationOnly) card.append(el("label", {}, "name"), name);
  card.append(el("label", {}, "location"), path, go, err);
  box.append(card);
}

async function loadLibraries(allowOnboard = false) {
  const libs = await rspc("libraries.list", null, null);
  const sel = document.getElementById("library");
  sel.innerHTML = "";
  for (const lib of libs) sel.append(el("option", {value: lib.id}, lib.name));
  if (libs.length) {
    // preserve the active selection across reloads (settings save must not
    // silently switch libraries); fall back to the first library
    if (!libs.some(l => l.id === state.library)) state.library = libs[0].id;
    sel.value = state.library;
    const locs = await loadLocations();
    // only a NAVIGATING refresh may replace the current view with the
    // onboarding card — passive sidebar refreshes (settings save) must not
    if (allowOnboard && !locs.length)
      showOnboarding(true, "add a location to index");
  } else if (allowOnboard) {
    showOnboarding();  // first run: guided library + location creation
  }
  sel.onchange = async () => {
    state.library = sel.value;
    state.location = null;  // locations are per-library
    state.dir = "/";
    await loadLocations();
    loadTags();
    loadAlbums();
  };
}

async function loadLocations() {  // returns the list
  const locs = await rspc("locations.list");
  const box = document.getElementById("locations");
  box.innerHTML = "";
  for (const loc of locs) {
    const row = el("div", {className: "loc"});
    row.append(el("span", {}, loc.name || loc.path));
    const scan = el("button", {title: "rescan"}, "↻");
    scan.onclick = async (e) => { e.stopPropagation();
      await rspc("locations.fullRescan", {location_id: loc.id}); };
    row.append(scan);
    row.onclick = () => { state.location = loc.id; state.dir = "/"; browse(); };
    box.append(row);
  }
  if (state.location === null) state.location = locs.length ? locs[0].id : null;
  browse();
  return locs;
}

function crumbs() {
  const c = document.getElementById("crumbs");
  c.innerHTML = "";
  const parts = state.dir.split("/").filter(Boolean);
  const root = el("a", {}, "root"); root.onclick = () => { state.dir = "/"; browse(); };
  c.append(root);
  let acc = "/";
  for (const part of parts) {
    acc += part + "/";
    const target = acc;
    c.append(document.createTextNode(" / "));
    const a = el("a", {}, part);
    a.onclick = () => { state.dir = target; browse(); };
    c.append(a);
  }
}

// ---- virtualized location grid -------------------------------------------
// A 100k-row directory must scroll with <200 live DOM nodes: #content
// becomes the scroll viewport over a spacer sized for the full row count,
// pages of 200 rows fetch on demand via search.paths{take, skip}, and only
// the visible window (plus a small buffer) materializes cards.
const VGRID = { rowH: 176, cellW: 152, page: 200, pages: new Map(),
                pending: new Set(), total: 0, epoch: 0, filters: null,
                spacer: null, fetchSeq: 0 };

async function browse() {
  if (state.library === null || state.location === null) return;
  state.ephemeralPath = null;  // leaving ephemeral view stops its retries
  crumbs();
  const epoch = ++VGRID.epoch;
  VGRID.pages.clear(); VGRID.pending.clear();
  VGRID.filters = {location_id: state.location,
                   materialized_path: state.dir, dirs_first: true};
  const total = await rspc("search.pathsCount", VGRID.filters);
  if (epoch !== VGRID.epoch) return;  // user switched views mid-count
  VGRID.total = total;
  const box = document.getElementById("content");
  box.className = "vgrid";
  box.innerHTML = "";
  VGRID.spacer = el("div");
  VGRID.spacer.style.position = "relative";
  box.append(VGRID.spacer);
  box.onscroll = () => requestAnimationFrame(renderWindow);
  window.onresize = () => requestAnimationFrame(renderWindow);
  renderWindow();
}

async function ensurePage(p) {
  if (VGRID.pages.has(p) || VGRID.pending.has(p)) return;
  VGRID.pending.add(p);
  const epoch = VGRID.epoch;
  try {
    const res = await rspc("search.paths",
      {...VGRID.filters, take: VGRID.page, skip: p * VGRID.page});
    if (epoch !== VGRID.epoch) return;  // view changed mid-flight
    VGRID.pages.set(p, res.items ?? res);
    VGRID.fetchSeq++;  // loaded-page state changed (set or evict below)
    if (VGRID.pages.size > 24) {  // bound memory: evict farthest pages
      const keep = [...VGRID.pages.keys()].sort((a, b) =>
        Math.abs(a - p) - Math.abs(b - p)).slice(0, 16);
      const keepSet = new Set(keep);
      for (const k of [...VGRID.pages.keys()])
        if (!keepSet.has(k)) VGRID.pages.delete(k);
    }
    renderWindow();
  } finally {
    if (epoch === VGRID.epoch) VGRID.pending.delete(p);
  }
}

function renderWindow() {
  const box = document.getElementById("content");
  if (box.className !== "vgrid" || !VGRID.spacer) return;
  const cols = Math.max(1, Math.floor(box.clientWidth / VGRID.cellW));
  const rows = Math.ceil(VGRID.total / cols);
  VGRID.spacer.style.height = `${rows * VGRID.rowH}px`;
  const first = Math.max(0, Math.floor(box.scrollTop / VGRID.rowH) - 2);
  const last = Math.min(rows,
    Math.ceil((box.scrollTop + box.clientHeight) / VGRID.rowH) + 2);
  // scroll fires per animation frame: rebuilding identical cards would
  // churn the DOM and re-decode thumbnails for nothing. fetchSeq (not
  // pages.size) keys the loaded-page state: after eviction cycles two
  // different page *sets* can share a size, and a size-keyed memo would
  // skip a freshly fetched page and leave holes until the next scroll.
  const sig = `${VGRID.epoch}:${first}:${last}:${cols}:${VGRID.fetchSeq}`;
  if (sig === VGRID.lastSig) return;
  VGRID.lastSig = sig;
  VGRID.spacer.innerHTML = "";
  for (let row = first; row < last; row++) {
    for (let col = 0; col < cols; col++) {
      const idx = row * cols + col;
      if (idx >= VGRID.total) break;
      const p = Math.floor(idx / VGRID.page);
      const pageItems = VGRID.pages.get(p);
      if (pageItems === undefined) { ensurePage(p); continue; }
      const it = pageItems[idx - p * VGRID.page];
      if (!it || !it.name) continue;
      const card = makeCard(it);
      card.classList.add("vcard");
      card.style.top = `${row * VGRID.rowH}px`;
      card.style.left = `${col * VGRID.cellW}px`;
      VGRID.spacer.append(card);
    }
  }
  if (!VGRID.total)
    VGRID.spacer.append(el("div", {className: "meta"}, "empty"));
}

function render(items) {
  state.ephemeralPath = null;  // any view switch stops ephemeral retries
  const box = document.getElementById("content");
  box.className = "grid";
  box.innerHTML = "";
  items.sort((a, b) => (b.is_dir - a.is_dir)
    || (a.name ?? "").localeCompare(b.name ?? ""));
  for (const it of items) {
    if (!it.name) continue;
    box.append(makeCard(it));
  }
  if (!items.length) box.append(el("div", {className: "meta"}, "empty"));
}

function makeCard(it) {
    const card = el("div", {className: "item"});
    const thumb = el("div", {className: "thumb"});
    if (it.cas_id && (it.object_kind === 5 || it.object_kind === 7)) {
      const img = el("img", {loading: "lazy",
        src: `/spacedrive/thumbnail/${it.cas_id.slice(0,2)}/${it.cas_id}.webp`});
      img.onerror = () => { thumb.textContent = KIND_ICONS[it.object_kind]; };
      thumb.append(img);
    } else {
      thumb.textContent = KIND_ICONS[it.is_dir ? 2 : (it.object_kind ?? 0)] || "📄";
    }
    const full = it.name + (it.extension && !it.is_dir ? "." + it.extension : "");
    card.append(thumb, el("div", {className: "name", title: full}, full),
      el("div", {className: "meta"},
         it.is_dir ? "folder" : fmtSize(it.size_in_bytes)));
    if (!it.is_dir && it.object_id != null) {
      const fav = el("span",
        {className: "fav" + (it.favorite ? " on" : ""),
         title: "favorite"}, it.favorite ? "★" : "☆");
      fav.onclick = async (e) => { e.stopPropagation();
        await rspc("files.setFavorite",
          {object_id: it.object_id, favorite: !it.favorite});
        it.favorite = !it.favorite;
        fav.textContent = it.favorite ? "★" : "☆";
        fav.className = "fav" + (it.favorite ? " on" : "");
      };
      card.append(fav);
      card.oncontextmenu = (e) => {
        e.preventDefault();
        contextMenu(e.pageX, e.pageY, [
          ["tag…", async () => {
            const name = prompt(`tag "${full}" with:`);
            if (!name) return;
            const tags = await rspc("tags.list");
            let tag = tags.find(t => t.name === name);
            if (!tag) tag = await rspc("tags.create", {name});
            await rspc("tags.assign",
              {tag_id: tag.id, object_ids: [it.object_id], unassign: false});
            loadTags();
          }],
          ["add to album…", async () => {
            const name = prompt(`add "${full}" to album:`);
            if (!name) return;
            const albums = await rspc("albums.list");
            let album = albums.find(a => a.name === name);
            if (!album) album = await rspc("albums.create", {name});
            await rspc("albums.addObjects",
              {id: album.id, object_ids: [it.object_id]});
            loadAlbums();
          }],
          ["label…", async () => {
            const name = prompt(`label "${full}" as:`);
            if (!name) return;
            await rspc("labels.assign",
              {name, object_ids: [it.object_id]});
          }],
        ]);
      };
    }
    card.onclick = () => {
      if (it.is_dir) {
        state.location = it.location_id;  // search results may span locations
        state.dir = `${it.materialized_path}${it.name}/`;
        browse();
      }
      else quickPreview(it);
    };
    return card;
}

// ---- quick preview (interface/app Explorer QuickPreview role) ------------
function closePreview() {
  const p = document.getElementById("preview");
  if (p) p.remove();
  document.onkeydown = null;
}

function quickPreview(it) {
  closePreview();
  const fileUrl =
    `/spacedrive/file/${state.library}/${it.location_id}/${it.id}`;
  const full = it.name + (it.extension && !it.is_dir ? "." + it.extension : "");
  const overlay = el("div", {id: "preview"});
  overlay.onclick = (e) => { if (e.target === overlay) closePreview(); };
  document.onkeydown = (e) => { if (e.key === "Escape") closePreview(); };
  const media = el("div", {className: "media"});
  const kind = it.object_kind ?? 0;
  const ext = (it.extension || "").toLowerCase();
  if (kind === 5) {                         // image: the original renders
    const img = el("img", {src: fileUrl});
    img.onerror = () => { media.textContent = KIND_ICONS[kind] || "📄"; };
    media.append(img);
  } else if (kind === 7) {                  // video plays regardless; the
    const vid = el("video", {controls: true, src: fileUrl});  // thumb only
    if (it.cas_id)                          // supplies the poster
      vid.poster = `/spacedrive/thumbnail/${it.cas_id.slice(0,2)}/${it.cas_id}.webp`;
    media.append(vid);
  } else if (kind === 6) {                  // audio
    media.append(el("audio", {controls: true, src: fileUrl}));
  } else if (kind === 3 || ["txt","md","json","py","ts","js","css","html",
                            "yml","yaml","toml","csv","log"].includes(ext)) {
    const pre = el("pre", {}, "loading…");
    media.append(pre);
    // fills in asynchronously AFTER the overlay is on screen (below)
    fetch(fileUrl, {headers: {Range: "bytes=0-16383"}}).then(async (r) => {
      pre.textContent = r.ok ? await r.text()
                             : `read failed (${r.status}): ${await r.text()}`;
    }).catch((e) => { pre.textContent = `unreadable: ${e}`; });
  } else {
    media.append(el("div", {style: "font-size:64px"},
                    KIND_ICONS[kind] || "📄"));
  }
  const side = el("div", {className: "side"});
  side.append(el("h3", {}, full));
  // textContent only: filenames are attacker-controlled, never innerHTML
  const kv = (k, v) => {
    const row = el("div", {className: "kv"});
    row.append(el("b", {}, k), document.createTextNode(" " + (v ?? "—")));
    side.append(row);
  };
  kv("size", fmtSize(it.size_in_bytes));
  kv("kind", String(kind));
  kv("cas_id", it.cas_id ?? "—");
  kv("path", `${it.materialized_path ?? ""}${full}`);
  const fav = el("button", {}, it.favorite ? "★ unfavorite" : "☆ favorite");
  fav.onclick = async () => {
    await rspc("files.setFavorite",
      {object_id: it.object_id, favorite: !it.favorite});
    it.favorite = !it.favorite;
    fav.textContent = it.favorite ? "★ unfavorite" : "☆ favorite";
  };
  const note = el("textarea", {placeholder: "note…", value: it.note ?? ""});
  const saveNote = el("button", {}, "save note");
  saveNote.onclick = async () => {
    await rspc("files.setNote", {object_id: it.object_id, note: note.value});
    saveNote.textContent = "saved ✓";
  };
  const open = el("button", {}, "open original ↗");
  open.onclick = () => window.open(fileUrl, "_blank");
  if (it.object_id != null) side.append(fav, note, saveNote);
  side.append(open);
  const panel = el("div", {className: "panel"});
  panel.append(media, side);
  overlay.append(panel);
  document.body.append(overlay);
}

function fmtSize(n) {
  if (n == null) return "";
  const units = ["B","KiB","MiB","GiB","TiB"];
  let i = 0; while (n >= 1024 && i < units.length - 1) { n /= 1024; i++; }
  return `${n.toFixed(n >= 10 || i === 0 ? 0 : 1)} ${units[i]}`;
}

document.getElementById("search").addEventListener("keydown", async (e) => {
  if (e.key !== "Enter") return;
  const res = await rspc("search.paths", {search: e.target.value, take: 200});
  document.getElementById("crumbs").textContent =
    `search: ${e.target.value}`;
  render(res.items ?? res);
});

document.querySelector('[data-view="duplicates"]').onclick = async () => {
  state.ephemeralPath = null;
  const pairs = await rspc("search.duplicates", {});
  const box = document.getElementById("content");
  box.className = ""; box.innerHTML = "";
  document.getElementById("crumbs").textContent = "near-duplicate pairs";
  const table = el("table");
  table.append(el("tr", {innerHTML:
    "<th>similarity</th><th>file a</th><th>file b</th>"}));
  for (const p of pairs) {
    const tr = el("tr");
    tr.append(el("td", {}, p.similarity.toFixed(2)),
              el("td", {}, `${p.a_dir}${p.a_name}.${p.a_ext ?? ""}`),
              el("td", {}, `${p.b_dir}${p.b_name}.${p.b_ext ?? ""}`));
    table.append(tr);
  }
  if (!pairs.length) table.append(el("tr", {innerHTML:
    "<td colspan=3>no pairs recorded</td>"}));
  box.append(table);
};

document.querySelector('[data-view="overview"]').onclick = async () => {
  state.ephemeralPath = null;
  const [stats, cats] = await Promise.all([
    rspc("libraries.statistics"), rspc("categories.list")]);
  const box = document.getElementById("content");
  box.className = ""; box.innerHTML = "";
  document.getElementById("crumbs").textContent = "overview";
  const tiles = el("div", {className: "tiles"});
  const tile = (k, v) => {
    const t = el("div", {className: "tile"});
    t.append(el("div", {className: "v"}, v), el("div", {className: "k"}, k));
    return t;
  };
  tiles.append(
    tile("objects", String(stats.total_object_count ?? 0)),
    tile("unique content", fmtSize(Number(stats.total_unique_bytes ?? 0))),
    tile("total indexed", fmtSize(Number(stats.total_bytes_used ?? 0))),
    tile("previews", fmtSize(Number(stats.preview_media_bytes ?? 0))),
    tile("disk free", fmtSize(Number(stats.total_bytes_free ?? 0))));
  box.append(tiles);
  const table = el("table");
  table.append(el("tr", {innerHTML: "<th>category</th><th>objects</th>"}));
  for (const c of cats) {
    if (!c.count) continue;
    const tr = el("tr", {style: "cursor:pointer"});
    tr.append(el("td", {}, c.category), el("td", {}, String(c.count)));
    tr.onclick = async () => {
      const arg = c.category === "Favorites" ? {favorite: true, take: 500}
                                             : {kinds: c.kinds, take: 500};
      const res = await rspc("search.paths", arg);
      document.getElementById("crumbs").textContent =
        `category: ${c.category}`;
      render(res.items ?? res);
    };
    table.append(tr);
  }
  box.append(table);
};

// non-indexed browsing (search.ephemeralPaths): any host directory, with
// on-the-fly thumbnails, no library writes
async function browseEphemeral(path) {
  const res = await rspc("search.ephemeralPaths",
    {path, with_thumbnails: true}, null);
  state.ephemeralPath = path;
  const c = document.getElementById("crumbs");
  c.innerHTML = "";
  let acc = "";
  for (const part of path.split("/").filter(Boolean)) {
    acc += "/" + part;
    const target = acc;
    c.append(document.createTextNode(" / "));
    const a = el("a", {}, part);
    a.onclick = () => browseEphemeral(target);
    c.append(a);
  }
  c.append(document.createTextNode("  (not indexed)"));
  const errs = res.errors ?? [];
  const deferred = errs.some(e => String(e).includes("deferred"));
  if (errs.length) {
    const note = el("span", {className: "pill",
      title: errs.join("\n")},
      deferred ? " generating previews…" : ` ${errs.length} errors`);
    c.append(document.createTextNode(" "), note);
  }
  if (deferred) {
    // the endpoint caps preview generation per request — keep re-asking
    // while the user is still on this directory
    setTimeout(() => {
      if (state.ephemeralPath === path) browseEphemeral(path);
    }, 1200);
  }
  const box = document.getElementById("content");
  box.className = "grid";
  box.innerHTML = "";
  const entries = res.entries ?? [];
  entries.sort((a, b) => (b.is_dir - a.is_dir)
    || (a.name ?? "").localeCompare(b.name ?? ""));
  for (const it of entries) {
    const card = el("div", {className: "item"});
    const thumb = el("div", {className: "thumb"});
    if (it.has_thumbnail && it.cas_id) {
      const img = el("img", {loading: "lazy",
        src: `/spacedrive/thumbnail/${it.cas_id.slice(0,2)}/${it.cas_id}.webp`});
      img.onerror = () => { thumb.textContent = KIND_ICONS[it.kind] || "📄"; };
      thumb.append(img);
    } else {
      thumb.textContent = KIND_ICONS[it.is_dir ? 2 : (it.kind ?? 0)] || "📄";
    }
    const full = it.name + (it.extension && !it.is_dir ? "." + it.extension : "");
    card.append(thumb, el("div", {className: "name", title: it.path}, full),
      el("div", {className: "meta"},
         it.is_dir ? "folder" : fmtSize(it.size_in_bytes)));
    if (it.is_dir) card.onclick = () => browseEphemeral(it.path);
    box.append(card);
  }
  if (!entries.length) box.append(el("div", {className: "meta"}, "empty"));
}

document.querySelector('[data-view="history"]').onclick = async () => {
  state.ephemeralPath = null;
  const reports = await rspc("jobs.reports", {});
  const box = document.getElementById("content");
  box.className = ""; box.innerHTML = "";
  document.getElementById("crumbs").textContent = "job history";
  const table = el("table");
  table.append(el("tr", {innerHTML:
    "<th>job</th><th>status</th><th>tasks</th><th>started</th><th></th>"}));
  const addRow = (r, indent) => {
    const tr = el("tr");
    const done = r.completed_task_count ?? 0, total = r.task_count ?? 0;
    tr.append(
      el("td", {style: indent ? "padding-left:24px" : ""},
         (indent ? "↳ " : "") + (r.name || "job")),
      el("td", {}, String(r.status_name ?? r.status ?? "")),
      el("td", {}, `${done}/${total}`),
      el("td", {}, String(r.date_created ?? "").slice(0, 19)));
    const act = el("td");
    if (["Paused", "Queued"].includes(r.status_name)) {
      const resume = el("button", {}, "resume");
      resume.onclick = async () => { await rspc("jobs.resume", r.id);
        resume.textContent = "…"; };
      act.append(resume);
    }
    tr.append(act);
    table.append(tr);
  };
  for (const r of reports) {
    addRow(r, false);
    for (const c of r.children ?? []) addRow(c, true);
  }
  if (!reports.length) table.append(el("tr",
    {innerHTML: "<td colspan=5>no job reports</td>"}));
  const clear = el("button", {style: "margin-top:10px"}, "clear finished");
  clear.onclick = async () => { await rspc("jobs.clearAll", {});
    document.querySelector('[data-view="history"]').onclick(); };
  box.append(table, clear);
};

document.querySelector('[data-view="settings"]').onclick = async () => {
  state.ephemeralPath = null;
  const box = document.getElementById("content");
  box.className = "settings"; box.innerHTML = "";
  document.getElementById("crumbs").textContent = "settings";

  // ---- library edit (libraries.edit) ----
  const libs = await rspc("libraries.list", null, null);
  const lib = libs.find(l => l.id === state.library) || {};
  box.append(el("h3", {}, "Library"));
  const nameIn = el("input", {value: lib.name ?? ""});
  const descIn = el("input", {value: lib.description ?? ""});
  box.append(el("label", {}, "name"), nameIn,
             el("label", {}, "description"), descIn);
  const save = el("button", {}, "save library");
  save.onclick = async () => {
    await rspc("libraries.edit", {id: state.library, name: nameIn.value,
                                  description: descIn.value}, null);
    save.textContent = "saved ✓"; loadLibraries();
  };
  box.append(el("div", {}, ""), save);

  // ---- indexer rules (locations.indexer_rules.*) ----
  box.append(el("h3", {}, "Indexer rules"));
  const table = el("table");
  box.append(table);
  const KINDS = {0: "accept files by glob", 1: "reject files by glob",
                 2: "accept if child dirs present",
                 3: "reject if child dirs present"};
  async function refreshRules() {
    table.innerHTML = "";
    table.append(el("tr", {innerHTML:
      "<th>name</th><th>rules</th><th>system</th><th></th>"}));
    const rules = await rspc("locations.indexer_rules.list");
    for (const r of rules) {
      const tr = el("tr");
      const raw = r.rules_per_kind ?? r.rules;  // raw IndexerRule rows
      const ruleset = typeof raw === "string" ? JSON.parse(raw) : (raw ?? {});
      const desc = Object.entries(ruleset).map(([k, v]) =>
        `${KINDS[k] ?? k}: ${(v ?? []).join(", ")}`).join(" · ");
      tr.append(el("td", {}, r.name), el("td", {}, desc),
                el("td", {}, r.default ? "yes" : ""));
      const actions = el("td");
      if (!r.default) {
        const del = el("button", {}, "delete");
        del.onclick = async () => {
          await rspc("locations.indexer_rules.delete", r.id);
          refreshRules();
        };
        actions.append(del);
      }
      tr.append(actions);
      table.append(tr);
    }
  }
  await refreshRules();

  box.append(el("h3", {}, "New rule"));
  const rName = el("input", {placeholder: "rule name"});
  const rKind = el("select");
  for (const [v, label] of Object.entries(KINDS))
    rKind.append(el("option", {value: v}, label));
  const rParams = el("textarea",
    {placeholder: "one glob / directory name per line"});
  const add = el("button", {}, "create rule");
  add.onclick = async () => {
    const params = rParams.value.split("\n").map(s => s.trim())
      .filter(Boolean);
    if (!rName.value || !params.length) return;
    await rspc("locations.indexer_rules.create",
      {name: rName.value, rules: {[rKind.value]: params}});
    rName.value = ""; rParams.value = "";
    refreshRules();
  };
  box.append(el("label", {}, "name"), rName,
             el("label", {}, "kind"), rKind,
             el("label", {}, "parameters"), rParams,
             el("div", {}, ""), add);
};

document.querySelector('[data-view="ephemeral"]').onclick = () => {
  const path = prompt("absolute directory to browse:", "/");
  if (path) browseEphemeral(path);
};

let _menu = null;
function contextMenu(x, y, options) {
  if (_menu) _menu.remove();
  const m = el("div", {style: `position:absolute;left:${x}px;top:${y}px;` +
    "background:var(--panel2);border:1px solid #2e3040;border-radius:6px;" +
    "padding:4px;z-index:10;min-width:140px"});
  for (const [label, fn] of options) {
    const row = el("div", {className: "loc"}, label);
    row.onclick = () => { m.remove(); _menu = null; fn(); };
    m.append(row);
  }
  _menu = m;
  document.body.append(m);
  setTimeout(() => document.addEventListener("click", () => {
    if (_menu === m) { m.remove(); _menu = null; }
  }, {once: true}), 0);
}

async function loadAlbums() {
  const albums = await rspc("albums.list").catch(() => []);
  const box = document.getElementById("albums");
  box.innerHTML = "";
  for (const album of albums) {
    if (album.is_hidden) continue;
    const row = el("div", {className: "loc"});
    row.append(el("span", {}, album.name),
               el("span", {className: "pill"}, String(album.object_count)));
    row.onclick = async () => {
      const items = await rspc("albums.objects", album.id);
      document.getElementById("crumbs").textContent = `album: ${album.name}`;
      render(items);
    };
    box.append(row);
  }
  if (!albums.length)
    box.append(el("div", {className: "meta"}, "right-click a file to add"));
}

async function loadTags() {
  const tags = await rspc("tags.list").catch(() => []);
  const box = document.getElementById("tags");
  box.innerHTML = "";
  for (const tag of tags) {
    const row = el("div", {className: "loc"});
    const label = el("span");
    label.append(el("span", {className: "dot",
      style: tag.color ? `background:${tag.color}` : ""}),
      document.createTextNode(tag.name));
    row.append(label);
    row.onclick = async () => {
      const res = await rspc("search.paths", {tags: [tag.id], take: 500});
      document.getElementById("crumbs").textContent = `tag: ${tag.name}`;
      render(res.items ?? res);
    };
    box.append(row);
  }
  if (!tags.length)
    box.append(el("div", {className: "meta"}, "right-click a file to tag"));
}

async function loadPeers() {
  const peers = await rspc("p2p.peers", null, null).catch(() => []);
  const box = document.getElementById("peers");
  box.innerHTML = "";
  for (const p of peers) {
    const row = el("div", {className: "loc", title: p.identity});
    const label = el("span", {},
      (p.name || p.identity.slice(0, 10)) +
      ((p.accelerator || {}).devices ? " ⚡" : ""));
    row.append(label,
      el("span", {className: "pill"}, p.connected ? "online" : "seen"));
    if (p.connected) {
      const pair = el("button", {title: "pair libraries"}, "pair");
      pair.onclick = async () => {
        await rspc("p2p.pair", {peer_id: p.identity}, null);
        pair.textContent = "sent";
      };
      const drop = el("button", {title: "spacedrop a file"}, "drop");
      drop.onclick = async () => {
        const path = prompt("absolute path of the file to send:");
        if (!path) return;
        await rspc("p2p.spacedrop", {peer_id: p.identity, paths: [path]}, null);
        drop.textContent = "sent";
        setTimeout(() => { drop.textContent = "drop"; }, 3000);
      };
      row.append(pair, drop);
    }
    box.append(row);
  }
  if (!peers.length)
    box.className = "meta", box.textContent = "none discovered";
}
setInterval(loadPeers, 10000);

// live updates: jobs.progress + invalidation over the rspc websocket.
// ONE resubscribe interval lives outside connectWs (reconnects must not
// stack timers), and switching libraries stops the old progress stream.
let liveWs = null;
let subbedLib = null;
setInterval(() => {
  if (liveWs && liveWs.readyState === WebSocket.OPEN &&
      state.library && state.library !== subbedLib) {
    if (subbedLib !== null) {
      liveWs.send(JSON.stringify({id: 3, method: "subscriptionStop",
        params: {subscriptionId: 2}}));
    }
    subbedLib = state.library;
    liveWs.send(JSON.stringify({id: 2, method: "subscription",
      params: {path: "jobs.progress",
               input: {library_id: state.library, arg: null}}}));
  }
}, 500);

function connectWs() {
  const scheme = location.protocol === "https:" ? "wss" : "ws";
  const ws = new WebSocket(`${scheme}://${location.host}/rspc/ws`);
  liveWs = ws;
  const status = document.getElementById("status");
  const jobs = {};
  ws.onopen = () => {
    status.textContent = "live";
    ws.send(JSON.stringify({id: 1, method: "subscription",
      params: {path: "invalidation.listen", input: null}}));
    ws.send(JSON.stringify({id: 4, method: "subscription",
      params: {path: "p2p.events", input: null}}));
  };
  ws.onclose = () => {
    status.textContent = "disconnected — retrying…";
    subbedLib = null;
    setTimeout(connectWs, 2000);
  };
  ws.onmessage = (m) => {
    const msg = JSON.parse(m.data);
    const data = msg.result?.data;
    if (!data) return;
    if (msg.id === 2 && data.kind === "job_progress") {
      const p = data.payload || {};
      jobs[p.id] = p;
      const box = document.getElementById("jobs");
      box.innerHTML = "";
      for (const job of Object.values(jobs)) {
        const total = job.task_count || 1;
        const done = job.completed_task_count || 0;
        const row = el("div", {className: "job"});
        const head = el("div", {style: "display:flex;justify-content:space-between"});
        head.append(el("span", {}, `${job.name || "job"} `),
                    el("span", {className: "pill"}, `${done}/${total}`));
        row.append(head);
        const bar = el("div", {className: "bar"});
        bar.append(el("div", {style: `width:${100 * done / total}%`}));
        row.append(bar);
        if (done < total) {
          const ctl = el("div", {style: "margin-top:4px;display:flex;gap:4px"});
          const pause = el("button", {title: "pause"}, "⏸");
          pause.onclick = () => rspc("jobs.pause", job.id, null)
            .catch(() => rspc("jobs.resume", job.id).catch(() => {}));
          const cancel = el("button", {title: "cancel"}, "✕");
          cancel.onclick = () => rspc("jobs.cancel", job.id, null)
            .then(() => { delete jobs[job.id]; row.remove(); })
            .catch(() => {});
          ctl.append(pause, cancel);
          row.append(ctl);
        }
        if (done >= total) setTimeout(() => { delete jobs[job.id];
          row.remove(); }, 4000);
        box.append(row);
      }
    }
    if (msg.id === 4 && data.kind === "p2p") {
      const ev = data.payload || {};
      if (ev.type === "SpacedropRequest") {
        const ok = confirm(
          `Accept spacedrop "${ev.name}" (${fmtSize(ev.size)}) from ` +
          `${(ev.identity || "").slice(0, 10)}…?`);
        const dir = ok ? prompt("save into directory:", "/tmp") : null;
        rspc("p2p.acceptSpacedrop", {id: ev.id, target_dir: dir}, null);
      }
      if (["ConnectedPeer", "DisconnectedPeer", "DiscoveredPeer",
           "ExpiredPeer"].includes(ev.type)) loadPeers();
    }
    if (msg.id === 1 && data.kind === "invalidate_query") {
      const key = data.payload?.key;
      if (key === "search.paths") browse();
      if (key === "locations.list" || key === "libraries.list") loadLocations();
      if (key === "search.duplicates") { /* view refreshes on click */ }
    }
  };
}

loadLibraries(true).then(() => { connectWs(); loadTags(); loadAlbums(); loadPeers(); })
  .catch(e => {
  document.getElementById("status").textContent = e.message;
});
</script>
</body>
</html>
"""
