"""Minimal HTTP/1.1 + WebSocket plumbing on asyncio streams.

The reference's server shell rides axum + tokio-tungstenite
(apps/server/src/main.rs:49-80); this environment has no baked-in HTTP
framework, so the shell carries its own small implementation: request
parsing, keep-alive, chunked-free fixed-length responses, byte-range file
streaming (the HttpRange behavior of custom_uri.rs), and RFC 6455 websocket
upgrade + frames (text/close/ping/pong, client-masked).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import io
import logging
import struct
import urllib.parse
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, AsyncIterator

from .. import telemetry

logger = logging.getLogger(__name__)

#: websocket rspc traffic volume (ISSUE 10) — message counts per
#: direction; per-procedure attribution lives in the sd_rspc_* families
_WS_MESSAGES = telemetry.counter(
    "sd_http_ws_messages_total",
    "websocket text messages by direction (in = client frames, out = "
    "responses/subscription events)", labels=("direction",))

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024
FILE_CHUNK = 256 * 1024

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

STATUS_TEXT = {
    200: "OK", 101: "Switching Protocols", 204: "No Content",
    206: "Partial Content", 400: "Bad Request", 401: "Unauthorized",
    404: "Not Found", 405: "Method Not Allowed",
    416: "Range Not Satisfiable", 500: "Internal Server Error",
}


class HttpError(Exception):
    def __init__(self, status: int, message: str = "") -> None:
        super().__init__(message or STATUS_TEXT.get(status, str(status)))
        self.status = status


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


@dataclass
class Response:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    #: when set, the body is streamed from this file path honoring `range`
    file_path: Path | None = None
    file_range: tuple[int, int] | None = None  # inclusive start, exclusive end

    @classmethod
    def json(cls, obj: Any, status: int = 200) -> "Response":
        import json as _json

        # default=str: DB rows surface datetimes; the wire gets ISO strings
        return cls(status, {"content-type": "application/json"},
                   _json.dumps(obj, default=str).encode())

    @classmethod
    def text(cls, s: str, status: int = 200) -> "Response":
        return cls(status, {"content-type": "text/plain; charset=utf-8"}, s.encode())

    @classmethod
    def error(cls, status: int, message: str = "") -> "Response":
        return cls.json({"error": message or STATUS_TEXT.get(status, "")}, status)


async def read_request(reader: asyncio.StreamReader) -> Request | None:
    """Parse one request; None on clean EOF."""
    try:
        raw = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise HttpError(400, "truncated request head")
    except asyncio.LimitOverrunError:
        raise HttpError(400, "header section too large")
    if len(raw) > MAX_HEADER_BYTES:
        raise HttpError(400, "header section too large")
    head = raw.decode("latin-1").split("\r\n")
    try:
        method, target, _version = head[0].split(" ", 2)
    except ValueError:
        raise HttpError(400, "malformed request line")
    headers: dict[str, str] = {}
    for line in head[1:]:
        if not line:
            continue
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    parsed = urllib.parse.urlsplit(target)
    query = dict(urllib.parse.parse_qsl(parsed.query))
    try:
        length = int(headers.get("content-length", "0") or 0)
    except ValueError:
        raise HttpError(400, "malformed content-length")
    if length < 0 or length > MAX_BODY_BYTES:
        raise HttpError(400, "bad body length")
    body = await reader.readexactly(length) if length else b""
    return Request(method.upper(), urllib.parse.unquote(parsed.path),
                   query, headers, body)


def parse_range(header: str, size: int) -> tuple[int, int] | None:
    """`Range: bytes=a-b` → (start, end_exclusive); None = whole file.
    Raises HttpError(416) on unsatisfiable ranges (custom_uri HttpRange)."""
    if not header:
        return None
    if not header.startswith("bytes="):
        raise HttpError(416, "unsupported range unit")
    spec = header[len("bytes="):].split(",")[0].strip()
    start_s, _, end_s = spec.partition("-")
    try:
        if start_s == "":  # suffix range: last N bytes
            n = int(end_s)
            if n <= 0:
                raise ValueError
            return max(0, size - n), size
        start = int(start_s)
        end = int(end_s) + 1 if end_s else size
    except ValueError:
        raise HttpError(416, "malformed range")
    if start >= size or start < 0 or end <= start:
        raise HttpError(416, "range out of bounds")
    return start, min(end, size)


async def write_response(writer: asyncio.StreamWriter, req: Request,
                         resp: Response) -> None:
    headers = dict(resp.headers)
    if resp.file_path is not None:
        size = resp.file_path.stat().st_size
        rng = resp.file_range
        if rng is None:
            start, end = 0, size
        else:
            start, end = rng
            resp.status = 206
            headers["content-range"] = f"bytes {start}-{end - 1}/{size}"
        headers.setdefault("accept-ranges", "bytes")
        headers["content-length"] = str(end - start)
        _write_head(writer, resp.status, headers)
        if req.method != "HEAD":
            with open(resp.file_path, "rb") as fh:
                fh.seek(start)
                left = end - start
                while left > 0:
                    chunk = fh.read(min(FILE_CHUNK, left))
                    if not chunk:
                        break
                    writer.write(chunk)
                    await writer.drain()
                    left -= len(chunk)
        return
    headers["content-length"] = str(len(resp.body))
    _write_head(writer, resp.status, headers)
    if req.method != "HEAD":
        writer.write(resp.body)
    await writer.drain()


def _write_head(writer: asyncio.StreamWriter, status: int,
                headers: dict[str, str]) -> None:
    lines = [f"HTTP/1.1 {status} {STATUS_TEXT.get(status, '')}"]
    for name, value in headers.items():
        lines.append(f"{name}: {value}")
    writer.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin-1"))


# ---------------------------------------------------------------------------
# WebSocket (RFC 6455)
# ---------------------------------------------------------------------------

class WebSocket:
    """Server-side socket after upgrade. Text frames carry JSON-RPC."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self._reader = reader
        self._writer = writer
        self.closed = False

    @staticmethod
    def accept_key(client_key: str) -> str:
        digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
        return base64.b64encode(digest).decode()

    async def send_text(self, text: str) -> None:
        if self.closed:
            return
        _WS_MESSAGES.inc(direction="out")
        await self._send_frame(0x1, text.encode())

    async def _send_frame(self, opcode: int, payload: bytes) -> None:
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(n)
        elif n < 1 << 16:
            head.append(126)
            head += struct.pack(">H", n)
        else:
            head.append(127)
            head += struct.pack(">Q", n)
        self._writer.write(bytes(head) + payload)
        await self._writer.drain()

    async def recv(self) -> str | None:
        """Next text message (handles ping/pong/continuation); None on close."""
        message = io.BytesIO()
        opcode_in_progress = None
        while True:
            try:
                b1, b2 = await self._reader.readexactly(2)
            except (asyncio.IncompleteReadError, ConnectionError):
                self.closed = True
                return None
            fin, opcode = b1 & 0x80, b1 & 0x0F
            masked, length = b2 & 0x80, b2 & 0x7F
            if length == 126:
                (length,) = struct.unpack(">H", await self._reader.readexactly(2))
            elif length == 127:
                (length,) = struct.unpack(">Q", await self._reader.readexactly(8))
            if length > MAX_BODY_BYTES:
                await self.close(1009)
                return None
            mask = await self._reader.readexactly(4) if masked else b"\x00" * 4
            payload = bytearray(await self._reader.readexactly(length))
            if masked:
                for i in range(len(payload)):
                    payload[i] ^= mask[i & 3]
            if opcode == 0x8:  # close
                await self.close()
                return None
            if opcode == 0x9:  # ping → pong
                await self._send_frame(0xA, bytes(payload))
                continue
            if opcode == 0xA:  # pong
                continue
            if opcode in (0x1, 0x2):
                opcode_in_progress = opcode
                message = io.BytesIO()
            elif opcode != 0x0 or opcode_in_progress is None:
                await self.close(1002)
                return None
            message.write(bytes(payload))
            if fin:
                data = message.getvalue()
                _WS_MESSAGES.inc(direction="in")
                if opcode_in_progress == 0x1:
                    return data.decode("utf-8", errors="replace")
                return data.decode("latin-1")  # binary surfaced as text rpc

    async def close(self, code: int = 1000) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            await self._send_frame(0x8, struct.pack(">H", code))
        except (ConnectionError, RuntimeError):
            pass


async def messages(ws: WebSocket) -> AsyncIterator[str]:
    while True:
        msg = await ws.recv()
        if msg is None:
            return
        yield msg
