"""Headless server shell: HTTP + websocket transport over the Node's router.

Reference: apps/server/src/main.rs:49-80 (axum: `/health`, `/spacedrive`
custom_uri, `/rspc` websocket) and core/src/custom_uri.rs:84 (streaming
file/thumbnail server with HttpRange partial content and remote-over-p2p
serving). This is the process boundary the reference's entire frontend
contract crosses; procedures resolve on a worker-thread pool so slow DB
work never stalls the accept loop.

Routes:
    GET  /                                         → embedded web explorer
    GET  /health                                   → "OK"
    GET  /info                                     → server/node JSON
    GET  /rspc/<key>?arg=<json>[&library_id=]      → query
    POST /rspc/<key>   {"arg":..,"library_id":..}  → query or mutation
    GET  /rspc/ws (Upgrade: websocket)             → JSON-RPC incl. subscriptions
    GET  /spacedrive/thumbnail/<shard>/<cas>.webp  → thumbnail cache, ranged
    GET  /spacedrive/file/<library>/<loc>/<fp_id>  → file bytes, ranged;
         owned by another instance → fetched over the p2p File header
    GET  /schema                                   → router schema export

websocket JSON-RPC (the rspc wire shape, packages/client core.ts):
    → {"id":1,"method":"query"|"mutation","params":{"path":k,"input":..}}
    ← {"jsonrpc":"2.0","id":1,"result":{"type":"response","data":..}}
    → {"id":2,"method":"subscription","params":{"path":k,"input":..}}
    ← {"jsonrpc":"2.0","id":2,"result":{"type":"event","data":..}} (each event)
    → {"id":3,"method":"subscriptionStop","params":{"subscriptionId":2}}
Library-scoped procedures take input = {"library_id":.., "arg":..} — the
LibraryArgs<T> envelope (api/utils/library.rs:50).
"""

from __future__ import annotations

import asyncio
import base64
import io
import json
import logging
import secrets
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import TYPE_CHECKING, Any

from .. import telemetry
from ..api.router import ApiError, RawJson
from ..telemetry.requests import REQUEST_BUCKETS, record_payload
from .http import (
    HttpError,
    Request,
    Response,
    WebSocket,
    parse_range,
    read_request,
    write_response,
)

if TYPE_CHECKING:
    from ..node import Node

logger = logging.getLogger(__name__)

#: cap for spooled remote-over-p2p serves (see _serve_remote)
MAX_REMOTE_SPOOL = 64 * 1024 * 1024

#: HTTP-layer families (ISSUE 10): the route label is a small CLOSED set
#: (the shell's own top-level routes), never the raw path — cardinality
#: stays bounded no matter what clients request
_HTTP_ROUTES = {"health", "metrics", "info", "rspc", "schema", "client",
                "spacedrive"}
_HTTP_REQUESTS = telemetry.counter(
    "sd_http_requests_total",
    "HTTP requests served by the shell, by route class and status",
    labels=("route", "status"))
_HTTP_SECONDS = telemetry.histogram(
    "sd_http_request_seconds", "HTTP request latency per route class",
    labels=("route",), buckets=REQUEST_BUCKETS)
_HTTP_BYTES = telemetry.counter(
    "sd_http_response_bytes_total",
    "response payload bytes per route class (file/range streams count "
    "the streamed window)", labels=("route",))


def _route_class(path: str) -> str:
    head = path.split("/", 2)[1] if path.startswith("/") else path
    if path == "/telemetry/stream":
        return "stream"
    if not head:
        return "root"
    return head if head in _HTTP_ROUTES else "other"


class Server:
    def __init__(self, node: "Node", host: str = "127.0.0.1", port: int = 8080,
                 auth: str | None = None) -> None:
        """``auth``: optional "user:password" enabling basic auth on every
        route except /health (the reference server's basic-auth util)."""
        self.node = node
        self.host = host
        self.port = port
        self.auth = auth
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="rspc")
        #: cas_id → monotonic deadline: remote-thumbnail misses we won't
        #: re-chase until the deadline passes
        self._thumb_miss: dict[str, float] = {}
        #: cas_id → future resolved when its in-flight remote fetch ends
        self._thumb_fetch: dict[str, asyncio.Future] = {}
        #: live SSE tails: (stop event, pump thread, bus subscription) per
        #: open /telemetry/stream — stop() closes and JOINS them, so a
        #: shell shutdown never strands pump threads parked on the bus
        #: (ISSUE 10 satellite; the threads were daemon-and-forgotten)
        self._sse_tails: set[tuple[threading.Event, threading.Thread, Any]] = set()
        self._sse_lock = threading.Lock()
        self._ready = threading.Event()
        self._owns_pool = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Run the accept loop on a dedicated thread; returns once bound.

        Starting the shell is what makes this node a SERVING node, so it
        also brings up the multi-process reader pool (ISSUE 11) unless
        one is already attached or ``SD_SERVE_WORKERS=0`` keeps the
        degraded in-process mode. Forking happens here, before the
        accept loop exists — workers inherit the loaded interpreter, not
        the server socket traffic."""
        if getattr(self.node, "reader_pool", None) is None:
            from .pool import ReaderPool

            pool = ReaderPool.maybe_start(self.node)
            if pool is not None:
                self.node.reader_pool = pool
                self._owns_pool = True
        self._thread = threading.Thread(target=self._run, name="sd-server",
                                        daemon=True)
        self._thread.start()
        if not self._ready.wait(10):
            raise RuntimeError("server failed to bind")

    def _run(self) -> None:
        asyncio.run(self._serve())

    async def _serve(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._client, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self._ready.set()
        logger.info("server listening on %s:%s", self.host, self.port)
        async with self._server:
            try:
                await self._server.serve_forever()
            except asyncio.CancelledError:
                pass

    def stop(self) -> None:
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            try:
                loop.call_soon_threadsafe(server.close)
                # serve_forever unblocks when the server closes
                loop.call_soon_threadsafe(
                    lambda: [t.cancel() for t in asyncio.all_tasks(loop)])
            except RuntimeError:
                pass  # loop already closed (shutdown race) — nothing to stop
        # SSE pump threads park on the event bus for up to their poll
        # timeout — stop and JOIN them (closing the subscription wakes the
        # blocking get immediately), so shutdown leaves no tail behind
        with self._sse_lock:
            tails = list(self._sse_tails)
        for stop_event, thread, sub in tails:
            stop_event.set()
            sub.close()
        for _stop_event, thread, _sub in tails:
            # is_alive() also guards the registered-but-not-yet-started
            # window: join() on an unstarted thread raises and would
            # abort the rest of shutdown (the woken pump exits on its
            # first stop/closed check either way)
            if thread.is_alive():
                thread.join(timeout=5)
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)
        if self._owns_pool and getattr(self.node, "reader_pool", None) \
                is not None:
            self.node.reader_pool.stop()
            self.node.reader_pool = None
            self._owns_pool = False

    # -- connection handling -------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await read_request(reader)
                except HttpError as e:
                    await write_response(
                        writer, Request("GET", "/", {}, {}, b""),
                        Response.error(e.status, str(e)))
                    break
                if req is None:
                    break
                if req.header("upgrade", "").lower() == "websocket":
                    try:
                        await self._websocket(req, reader, writer)
                    except HttpError as e:
                        await write_response(writer, req,
                                             Response.error(e.status, str(e)))
                    break
                if req.method == "GET" and req.path == "/telemetry/stream":
                    # SSE: the connection becomes a dedicated event stream
                    # (like the websocket branch above)
                    try:
                        await self._sse_stream(req, reader, writer)
                    except HttpError as e:
                        await write_response(writer, req,
                                             Response.error(e.status, str(e)))
                    break
                t0 = time.perf_counter()
                try:
                    resp = await self._route(req)
                except HttpError as e:
                    resp = Response.error(e.status, str(e))
                    if e.status == 401:
                        resp.headers["www-authenticate"] = \
                            'Basic realm="spacedrive"'
                except ApiError as e:
                    resp = Response.error(400, str(e))
                except Exception:
                    logger.exception("request failed: %s %s", req.method, req.path)
                    resp = Response.error(500)
                _observe_http(req, resp, time.perf_counter() - t0)
                await write_response(writer, req, resp)
                if req.header("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass

    def _check_auth(self, req: Request) -> None:
        if self.auth is None:
            return
        header = req.header("authorization")
        expect = "Basic " + base64.b64encode(self.auth.encode()).decode()
        try:
            ok = secrets.compare_digest(header.encode("utf-8", "replace"),
                                        expect.encode())
        except Exception:
            ok = False
        if not ok:
            # the Basic challenge makes browsers show a credential prompt
            raise HttpError(401, "authentication required")

    async def _route(self, req: Request) -> Response:
        parts = [p for p in req.path.split("/") if p]
        if req.path == "/health":
            return Response.text("OK")
        self._check_auth(req)
        if req.path == "/metrics":
            # Prometheus text exposition of the unified registry (ISSUE 5);
            # renders in-memory state only — no DB, safe on the accept loop
            from .. import telemetry

            return Response(
                headers={"content-type":
                         "text/plain; version=0.0.4; charset=utf-8"},
                body=telemetry.render_prometheus().encode())
        if not parts:
            from .webui import INDEX_HTML

            return Response(headers={"content-type": "text/html; charset=utf-8"},
                            body=INDEX_HTML.encode())
        if parts[0] == "info":
            return Response.json({"server": "spacedrive_tpu",
                                  "node": self.node.config.get().get("name")})
        if parts[0] == "rspc":
            return await self._rspc_http(req, "/".join(parts[1:]))
        if parts[0] == "schema":
            return Response.json(self.node.router.schema())
        if parts[0] == "client" and len(parts) == 2 \
                and parts[1] in ("core.ts", "procedures.js", "ui.css"):
            # the GENERATED typed-client artifacts (api/codegen.py); the
            # explorer loads procedures.js and refuses unknown keys, so a
            # stale artifact fails loudly rather than silently
            from ..api.codegen import client_dir

            path = client_dir() / parts[1]
            if not path.exists():
                hint = ("run python -m spacedrive_tpu.api.codegen"
                        if parts[1] != "ui.css"
                        else "restore client/ui.css from the repository")
                raise HttpError(404, f"client artifact missing — {hint}")
            ctype = {"core.ts": "text/typescript",
                     "procedures.js": "text/javascript",
                     "ui.css": "text/css"}[parts[1]]
            # artifact reads follow the shell's off-loop rule: a cold-cache
            # read (or a stalled mount) must not stall the accept loop
            body = await asyncio.get_running_loop().run_in_executor(
                self._pool, path.read_bytes)
            return Response(headers={"content-type": f"{ctype}; charset=utf-8"},
                            body=body)
        if parts[0] == "spacedrive":
            return await self._custom_uri(req, parts[1:])
        raise HttpError(404)

    # -- rspc over plain HTTP ------------------------------------------------
    async def _rspc_http(self, req: Request, key: str) -> Response:
        if not key:
            raise HttpError(404)
        try:
            if req.method == "GET":
                # GET is side-effect-free: queries only (mutations need POST)
                proc = self.node.router.procedures.get(key)
                if proc is not None and proc.kind != "query":
                    raise HttpError(405, f"{key} is a {proc.kind}; use POST")
                arg = json.loads(req.query["arg"]) if "arg" in req.query else None
                library_id = req.query.get("library_id")
            elif req.method == "POST":
                payload = json.loads(req.body.decode() or "{}")
                arg = payload.get("arg")
                library_id = payload.get("library_id")
            else:
                raise HttpError(405)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise HttpError(400, f"malformed request payload: {e}")
        try:
            result = await self._resolve(key, arg, library_id, raw=True)
        except ApiError as e:
            resp = Response.json({"error": str(e)}, 400)
            if key in self.node.router.procedures:
                # MOUNTED keys only: a client-supplied ghost key must not
                # mint unbounded label cardinality
                record_payload(key, len(req.body), len(resp.body))
            return resp
        if isinstance(result, RawJson):
            # pool workers hand back wire bytes (encoded with the exact
            # json.dumps call Response.json makes) — splice them into the
            # envelope instead of decode + re-encode; the prefix matches
            # json.dumps' default ': ' separator, so the body is
            # byte-identical to the in-process encoding
            resp = Response(200, {"content-type": "application/json"},
                            b'{"result": ' + result.data + b"}")
        else:
            resp = Response.json({"result": result})
        # wire payload sizes per procedure (the router's observed() can't
        # see serialization — only the transport knows wire bytes)
        record_payload(key, len(req.body), len(resp.body))
        return resp

    async def _resolve(self, key: str, arg: Any, library_id: str | None,
                       raw: bool = False) -> Any:
        if self.auth is None:
            from ..api.routers.keys import SECRET_PROCEDURES

            if key in SECRET_PROCEDURES:
                raise ApiError(
                    f"{key} returns secret material and is disabled while "
                    "the server runs without auth — start the shell with "
                    "credentials (--auth / SD_DESKTOP_AUTH) to enable it")
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._pool,
            lambda: self.node.router.resolve(key, arg, library_id, raw=raw))

    # -- custom_uri (custom_uri.rs:84) ---------------------------------------
    async def _custom_uri(self, req: Request, parts: list[str]) -> Response:
        if req.method not in ("GET", "HEAD"):
            raise HttpError(405)
        if len(parts) == 3 and parts[0] == "thumbnail":
            from ..objects.media.thumbnail import thumbnail_dir

            shard, name = parts[1], parts[2]
            if "/" in name or ".." in name or ".." in shard:
                raise HttpError(400)
            path = Path(thumbnail_dir(self.node.data_dir)) / shard / name
            if not path.is_file() and name.endswith(".webp"):
                cas_id = name[:-len(".webp")]
                if shard != cas_id[:2]:
                    # a mis-sharded URL must not seed cache files the GC's
                    # canonical-path delete could never find
                    raise HttpError(404, "no such thumbnail")
                # preview owned by a paired node: fetch once over p2p into
                # the local cache (sync_preview_media, on demand)
                await self._fetch_remote_thumbnail(cas_id, path)
            if not path.is_file():
                raise HttpError(404, "no such thumbnail")
            rng = parse_range(req.header("range"), path.stat().st_size)
            return Response(headers={"content-type": "image/webp"},
                            file_path=path, file_range=rng)
        if len(parts) == 4 and parts[0] == "file":
            return await self._serve_file(req, parts[1], parts[2], parts[3])
        raise HttpError(404)

    async def _fetch_remote_thumbnail(self, cas_id: str, dest: Path) -> None:
        """Find which paired node owns content with this cas_id and pull its
        cached preview into ours (best-effort; a miss just 404s)."""
        from ..models import FilePath, Instance, Location

        p2p = self.node.p2p
        if p2p is None:
            return
        # negative cache: a gallery of misses must not re-run the multi-
        # library owner scan + p2p round trip on every rerender
        now = time.monotonic()
        deadline = self._thumb_miss.get(cas_id)
        if deadline is not None and now < deadline:
            return
        # in-flight dedup: concurrent requests for one cas_id (HEAD+GET,
        # shared tiles) await the same fetch instead of seeing a "miss"
        pending = self._thumb_fetch.get(cas_id)
        if pending is not None:
            await asyncio.shield(pending)
            return
        loop = asyncio.get_running_loop()
        self._thumb_fetch[cas_id] = loop.create_future()
        try:
            await self._fetch_remote_thumbnail_inner(cas_id, dest)
        finally:
            fut = self._thumb_fetch.pop(cas_id)
            fut.set_result(None)
            if not dest.is_file():
                if len(self._thumb_miss) > 4096:
                    self._thumb_miss = {
                        k: v for k, v in self._thumb_miss.items() if v > now}
                self._thumb_miss[cas_id] = time.monotonic() + 30.0

    async def _fetch_remote_thumbnail_inner(self, cas_id: str,
                                            dest: Path) -> None:
        from ..models import FilePath, Instance, Location

        p2p = self.node.p2p
        loop = asyncio.get_running_loop()

        def _find_owner():
            """Blocking DB scan — runs on the worker pool, not the accept
            loop (the shell's no-DB-on-the-loop rule)."""
            for library in self.node.libraries.list():
                row = library.db.find_one(FilePath, {"cas_id": cas_id})
                if row is None:
                    continue
                location = library.db.find_one(
                    Location, {"id": row["location_id"]})
                if location is None or location.get("instance_id") in (
                        None, library.instance_id):
                    continue  # local content: nothing to fetch
                instance = library.db.find_one(
                    Instance, {"id": location["instance_id"]})
                if instance is None:
                    continue
                # the owning NODE's handshake identity (instance identities
                # are per-library keys, not dialable peers)
                peer_id = instance.get("node_remote_identity")
                if peer_id and peer_id in p2p.peers:
                    yield library, peer_id

        for library, peer_id in await loop.run_in_executor(
                self._pool, lambda: list(_find_owner())):
            future = asyncio.run_coroutine_threadsafe(
                p2p.request_thumbnail(peer_id, library.id, cas_id), p2p._loop)
            try:
                # wrap_future awaits on the loop — a screenful of misses
                # must not park default-executor threads for the timeout
                body = await asyncio.wait_for(asyncio.wrap_future(future), 15)
            except Exception as e:
                logger.debug("remote thumbnail %s: %s", cas_id[:8], e)
                continue

            def _persist():
                dest.parent.mkdir(parents=True, exist_ok=True)
                tmp = dest.with_suffix(".tmp.webp")
                tmp.write_bytes(body)
                tmp.replace(dest)

            # disk writes follow the same off-loop rule as the DB scan
            await loop.run_in_executor(self._pool, _persist)
            self._thumb_miss.pop(cas_id, None)
            return

    async def _serve_file(self, req: Request, library_id: str,
                          location_id: str, file_path_id: str) -> Response:
        from ..models import FilePath, Instance, Location

        try:
            library = self.node.libraries.get(library_id)
        except KeyError:
            raise HttpError(404, "no such library")
        try:
            fp_id, loc_id = int(file_path_id), int(location_id)
        except ValueError:
            raise HttpError(400, "file/location ids must be integers")
        db = library.db
        row = db.find_one(FilePath, {"id": fp_id})
        if row is None or row["location_id"] != loc_id:
            raise HttpError(404, "no such file_path")
        location = db.find_one(Location, {"id": row["location_id"]})
        if location is None:
            raise HttpError(404, "no such location")

        if location.get("instance_id") not in (None, library.instance_id):
            return await self._serve_remote(req, library, location, row)

        from ..objects.fs import file_path_abs

        try:
            _row, path = file_path_abs(db, row["id"])
            size = path.stat().st_size
        except (OSError, ValueError) as e:
            raise HttpError(404, f"file missing on disk: {e}")
        rng = parse_range(req.header("range"), size)
        ext = (row.get("extension") or "").lower()
        return Response(headers={"content-type": _mime(ext)},
                        file_path=path, file_range=rng)

    async def _serve_remote(self, req: Request, library, location,
                            row) -> Response:
        """ServeFrom::Remote (custom_uri.rs:64-69): the location belongs to
        another instance — fetch the ranged bytes over the p2p File header."""
        from ..models import Instance
        from ..p2p.spaceblock import Range

        p2p = self.node.p2p
        if p2p is None:
            raise HttpError(404, "remote file and p2p is offline")
        instance = library.db.find_one(Instance, {"id": location["instance_id"]})
        if instance is None:
            raise HttpError(404, "unknown owning instance")
        peer_id = instance.get("node_remote_identity")
        if not peer_id:
            raise HttpError(404, "instance has no p2p identity")
        if peer_id not in p2p.peers:
            raise HttpError(404, "owning node is not connected")
        size = row.get("size_in_bytes") or 0
        rng = parse_range(req.header("range"), size) if size else None
        start, end = rng if rng else (0, size)
        # remote bytes are spooled before responding; bound the spool so a
        # handful of concurrent video fetches cannot OOM the shell — large
        # remote reads must come as ranged requests
        if end - start > MAX_REMOTE_SPOOL:
            raise HttpError(
                416, f"remote serve is capped at {MAX_REMOTE_SPOOL} bytes "
                     f"per request; use Range")
        sink = io.BytesIO()
        future = asyncio.run_coroutine_threadsafe(
            p2p.request_file(peer_id, library.id, row["pub_id"],
                             Range(start, end if rng else None), sink),
            p2p._loop)
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, lambda: future.result(60))
        except Exception as e:
            raise HttpError(404, f"remote fetch failed: {e}")
        body = sink.getvalue()
        headers = {"content-type": _mime((row.get("extension") or "").lower()),
                   "accept-ranges": "bytes"}
        status = 200
        if rng:
            headers["content-range"] = f"bytes {start}-{end - 1}/{size}"
            status = 206
        return Response(status, headers, body)

    # -- live telemetry over SSE (ISSUE 7) -----------------------------------
    async def _sse_stream(self, req: Request, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        """``GET /telemetry/stream`` — the flight-recorder event ring as a
        text/event-stream: one SSE message per telemetry event, ``id:`` =
        the ring's monotonic seq (a reconnecting tail passes it back as
        ``?after=<seq>`` or ``Last-Event-ID`` to replay what it missed),
        ``: keepalive`` comments while idle. Like the websocket
        subscription path, each stream gets its OWN pump thread — parking
        on the bus queue must never occupy the shared rspc worker pool
        (8 open tails would otherwise starve every HTTP query)."""
        self._check_auth(req)
        from .. import telemetry

        try:
            after = int(req.query.get("after")
                        or req.header("last-event-id") or -1)
        except ValueError:
            after = -1
        sub = self.node.events.subscribe()
        loop = asyncio.get_running_loop()
        stop = threading.Event()

        async def send(frame: bytes) -> None:
            writer.write(frame)
            await writer.drain()

        def pump() -> None:
            """Dedicated thread: blocking-drain the subscription into the
            socket (the ws `pump` shape)."""
            while not stop.is_set():
                event = sub.get(timeout=15.0)
                if sub.closed or stop.is_set():
                    return
                if event is None:  # idle: keep intermediaries from closing
                    frame = b": keepalive\n\n"
                elif event.kind != "telemetry.event":
                    continue
                else:
                    frame = self._sse_frame(event.payload or {})
                try:
                    # scheduling itself can raise once the loop is closed
                    # (shutdown race) — that's teardown, not a crash
                    fut = asyncio.run_coroutine_threadsafe(send(frame), loop)
                    fut.result(10)
                except Exception:
                    return  # client went away — the normal end of a tail
        thread = threading.Thread(target=pump, daemon=True,
                                  name="sse-telemetry")
        tail = (stop, thread, sub)
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"content-type: text/event-stream\r\n"
                         b"cache-control: no-cache\r\n"
                         b"connection: close\r\n\r\n")
            # counted at accept (the stream is long-lived — it never
            # reaches the per-request observation in the route loop)
            _HTTP_REQUESTS.inc(route="stream", status="200")
            # replay: everything in the bounded ring the tail has not seen
            # (subscribed BEFORE the replay read, so no gap in between —
            # an event landing during replay is at worst duplicated, and
            # consumers dedupe on seq)
            for record in telemetry.recent_events(
                    limit=256, after_seq=after if after >= 0 else None):
                writer.write(self._sse_frame(record))
            await writer.drain()
            with self._sse_lock:
                self._sse_tails.add(tail)
            thread.start()
            # hold the handler open until the client hangs up (EOF) — SSE
            # clients send nothing, so any read completing means teardown
            while await reader.read(1024):
                pass
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            stop.set()
            sub.close()
            with self._sse_lock:
                self._sse_tails.discard(tail)
            # NO join here: this finally runs ON the event loop, and the
            # pump may be waiting on a send scheduled onto this very loop
            # — joining would deadlock-then-timeout, freezing every other
            # client for the duration. The closed subscription wakes the
            # pump immediately (daemon; stop() owns the blocking join)

    @staticmethod
    def _sse_frame(record: dict) -> bytes:
        data = json.dumps(record, default=str)
        seq = record.get("seq")
        head = f"id: {seq}\n" if seq is not None else ""
        return f"{head}data: {data}\n\n".encode()

    # -- rspc over websocket -------------------------------------------------
    async def _websocket(self, req: Request, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        self._check_auth(req)
        key = req.header("sec-websocket-key")
        if not key:
            raise HttpError(400, "missing websocket key")
        accept = WebSocket.accept_key(key)
        writer.write(
            ("HTTP/1.1 101 Switching Protocols\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
        await writer.drain()
        ws = WebSocket(reader, writer)
        subs: dict[Any, tuple[Any, threading.Thread]] = {}
        loop = asyncio.get_running_loop()
        send_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with send_lock:
                await ws.send_text(json.dumps(obj, default=str))

        def pump(sub_id: Any, subscription) -> None:
            """Worker thread: blocking-drain a Subscription into the socket."""
            flt = getattr(subscription, "filter", None)
            for event in subscription:
                if flt is not None and not flt(event):
                    continue  # subscriptions stream only their own variants
                payload = {"jsonrpc": "2.0", "id": sub_id,
                           "result": {"type": "event", "data": _event_wire(event)}}
                fut = asyncio.run_coroutine_threadsafe(send(payload), loop)
                try:
                    fut.result(10)
                except Exception:
                    break

        try:
            while True:
                raw = await ws.recv()
                if raw is None:
                    break
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    await send({"jsonrpc": "2.0", "id": None,
                                "result": {"type": "error",
                                           "data": {"code": 400,
                                                    "message": "bad json"}}})
                    continue
                await self._ws_message(msg, send, subs, pump)
        finally:
            for subscription, thread in subs.values():
                subscription.close()
            for _subscription, thread in subs.values():
                thread.join(timeout=2)

    async def _ws_message(self, msg: dict, send, subs: dict, pump) -> None:
        msg_id = msg.get("id")
        method = msg.get("method")
        params = msg.get("params") or {}
        path = params.get("path", "")
        input_ = params.get("input")
        library_id, arg = _split_library_args(input_)

        async def reply_error(code: int, message: str) -> None:
            await send({"jsonrpc": "2.0", "id": msg_id,
                        "result": {"type": "error",
                                   "data": {"code": code, "message": message}}})

        if method in ("query", "mutation"):
            try:
                data = await self._resolve(path, arg, library_id)
            except ApiError as e:
                await reply_error(400, str(e))
                return
            except Exception:
                logger.exception("ws %s %s failed", method, path)
                await reply_error(500, "internal error")
                return
            await send({"jsonrpc": "2.0", "id": msg_id,
                        "result": {"type": "response", "data": data}})
        elif method == "subscription":
            try:
                subscription = self.node.router.subscribe(path, arg, library_id)
            except ApiError as e:
                await reply_error(400, str(e))
                return
            stale = subs.pop(msg_id, None)
            if stale is not None:
                stale[0].close()  # re-used id: stop the old stream first
            thread = threading.Thread(target=pump, args=(msg_id, subscription),
                                      name=f"ws-sub-{path}", daemon=True)
            subs[msg_id] = (subscription, thread)
            # ack BEFORE the pump starts so 'started' precedes any event
            await send({"jsonrpc": "2.0", "id": msg_id,
                        "result": {"type": "started"}})
            thread.start()
        elif method == "subscriptionStop":
            sub_id = params.get("subscriptionId", msg_id)
            pair = subs.pop(sub_id, None)
            if pair is not None:
                pair[0].close()
            await send({"jsonrpc": "2.0", "id": msg_id,
                        "result": {"type": "stopped"}})
        else:
            await reply_error(400, f"unknown method {method!r}")


def _observe_http(req: Request, resp: Response, duration_s: float) -> None:
    """Per-route HTTP accounting (label set bounded by _route_class).
    File/range responses count the streamed window, not the whole file."""
    if not telemetry.enabled():
        return
    route = _route_class(req.path)
    _HTTP_REQUESTS.inc(route=route, status=str(resp.status))
    _HTTP_SECONDS.observe(duration_s, route=route)
    if resp.file_path is not None:
        try:
            size = resp.file_path.stat().st_size
        except OSError:
            size = 0
        start, end = resp.file_range or (0, size)
        _HTTP_BYTES.inc(max(0, end - start), route=route)
    elif resp.body:
        _HTTP_BYTES.inc(len(resp.body), route=route)


def _split_library_args(input_: Any) -> tuple[str | None, Any]:
    """LibraryArgs envelope: {"library_id": .., "arg": ..} → (lib, arg)."""
    if isinstance(input_, dict) and "library_id" in input_:
        return input_["library_id"], input_.get("arg")
    return None, input_


def _event_wire(event: Any) -> Any:
    if hasattr(event, "kind"):
        return {"kind": event.kind, "payload": getattr(event, "payload", None),
                "library_id": getattr(event, "library_id", None)}
    return event


_MIME = {
    "webp": "image/webp", "png": "image/png", "jpg": "image/jpeg",
    "jpeg": "image/jpeg", "gif": "image/gif", "svg": "image/svg+xml",
    "mp4": "video/mp4", "webm": "video/webm", "mp3": "audio/mpeg",
    "pdf": "application/pdf", "txt": "text/plain", "json": "application/json",
    "html": "text/html",
}


def _mime(ext: str) -> str:
    return _MIME.get(ext, "application/octet-stream")
