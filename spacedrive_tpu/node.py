"""Node: the core runtime every shell embeds.

Equivalent of the reference's ``Node::new`` (core/src/lib.rs:77-135): construct
config, event bus, managers; then start them in dependency order — locations
actor → libraries init → job cold-resume → p2p (the reference warns the
ordering is deadlock-critical, lib.rs:126; here the same order keeps watchers
and resumed jobs from racing library load).

TPU-native addition: the node probes its accelerator inventory at boot and
records it in config (advertised to peers for remote-hasher routing).
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

from .config import ConfigManager, NodeConfig
from .events import EventBus
from .jobs import Jobs
from .library import Libraries

logger = logging.getLogger(__name__)


def _probe_accelerator(timeout: float = 25.0) -> dict[str, Any]:
    """Record device kind/count WITHOUT letting a wedged backend stall boot.

    ``jax.devices()`` on a tunneled/remote plugin can block indefinitely when
    the device service is unreachable, so the probe runs in a disposable
    subprocess with a hard deadline: a dead tunnel degrades to a CPU-only
    node instead of hanging every shell at startup."""
    import json
    import os
    import subprocess
    import sys

    none = {"kind": None, "devices": 0, "mesh": []}
    if "python" not in os.path.basename(sys.executable or ""):
        # embedded host (C FFI): sys.executable is the host binary, so the
        # subprocess probe can't run — probe in-process instead of leaving
        # a healthy accelerator undetected (accepting the hang risk the
        # subprocess path exists to avoid)
        try:
            import jax

            d = jax.devices()
            return {"kind": d[0].platform if d else None,
                    "devices": len(d), "mesh": [len(d)]}
        except Exception as e:
            logger.info("no accelerator available: %s", e)
            return none
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import json, jax; d = jax.devices(); "
             "print(json.dumps({'kind': d[0].platform if d else None, "
             "'devices': len(d), 'mesh': [len(d)]}))"],
            capture_output=True, timeout=timeout, text=True)
        if proc.returncode != 0:
            logger.info("no accelerator available: %s",
                        (proc.stderr or "").strip().splitlines()[-1:])
            return none
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        logger.warning("accelerator probe timed out after %.0fs (device "
                       "service unreachable?); continuing CPU-only", timeout)
        return none
    except Exception as e:  # no accelerator is fine; CPU hasher still works
        logger.info("no accelerator available: %s", e)
        return none


class Node:
    def __init__(self, data_dir: str | Path,
                 probe_accelerator: bool | None = None,
                 watch_locations: bool | None = None) -> None:
        import os
        import sys

        self.data_dir = Path(data_dir)
        self.data_dir.mkdir(parents=True, exist_ok=True)
        # rotating file log + stdout (Node::init_logger, lib.rs:137-194)
        from .utils.tracing import init_logger

        init_logger(self.data_dir)
        self.config = ConfigManager(NodeConfig.load(self.data_dir))
        # location-watcher feature gate (the reference's `location-watcher`
        # cargo feature, location/manager/mod.rs:23-32)
        if watch_locations is None:
            watch_locations = not os.environ.get("SD_NO_WATCHER")
        self.watch_locations = watch_locations
        if probe_accelerator is None:
            # env applies only when the caller didn't decide (like the
            # watcher gate); embedded hosts probe in-process, CLI hosts in
            # a deadline-guarded subprocess (_probe_accelerator)
            probe_accelerator = not os.environ.get("SD_NO_ACCEL_PROBE")
        self.events = EventBus()
        self.jobs = Jobs()
        self.libraries = Libraries(self.data_dir, node=self)
        self.locations = None  # attached by locations layer
        self.p2p = None  # attached by p2p layer
        # node-wide admission budget for the CRDT/p2p receive path: every
        # ingest source (p2p sync responder, remote hash serving, the
        # fleet harness) admits through this so overload sheds with an
        # explicit BUSY instead of buffering unboundedly
        from .sync.admission import IngestBudget

        self.ingest_budget = IngestBudget()
        # admission at rspc dispatch (ISSUE 20): the same fair-share
        # budget shape applied to the serving tier — the router sheds
        # over-budget dispatches with a 429 + retry-after instead of
        # queueing them unboundedly. SD_RSPC_ADMISSION=0 turns the gate
        # off (every dispatch admitted, e.g. for A/B benches).
        if os.environ.get("SD_RSPC_ADMISSION", "1") not in ("0", "off"):
            from .sync.admission import DispatchBudget

            self.dispatch_budget = DispatchBudget()
        else:
            self.dispatch_budget = None
        try:
            from .crypto.keymanager import KeyManager

            self.key_manager = KeyManager(self.data_dir / "keystore.json")
        except ImportError as e:
            # dependency-gated (no ``cryptography`` in the image): the node
            # runs scans/sync/media without a key manager; crypto jobs and
            # key routes fail at use instead of wedging boot
            logger.warning("crypto stack unavailable (%s); key manager "
                           "disabled", e)
            self.key_manager = None
        if self.key_manager is not None:
            try:
                # keyring-backed auto-unlock (crates/crypto keys/keyring
                # role): no-op unless the user enabled it on this keystore
                if self.key_manager.try_auto_unlock():
                    logger.info("key manager auto-unlocked from the OS keyring")
            except Exception:
                logger.exception("keyring auto-unlock failed; password unlock "
                                 "still available")
        from .objects.gc import ThumbnailRemoverActor

        self.thumbnail_remover = ThumbnailRemoverActor(self)

        # multi-process reader pool (ISSUE 11): attached by the server
        # shell (or tests) via server/pool.ReaderPool — None means every
        # query resolves in-process (the degraded mode). The router reads
        # this attribute on each pool-marked query dispatch.
        self.reader_pool = None
        # distributed replica rung (ISSUE 19): when armed, pool-marked
        # queries may be served by watermark-eligible mesh peers before
        # the local pool — the top of the degradation ladder. Wired after
        # p2p boots; the fleet harness installs wire-less routers here.
        self.replica_router = None

        accel = None
        if probe_accelerator:
            # inventory only — deliberately NOT seeding the jax guard: the
            # boot->first-job gap can be hours, and a relay that dies in
            # between must be caught by the guard's own probe at first
            # device touch (a boot-time success would make it vacuous)
            accel = _probe_accelerator()
            self.config.write(accelerator=accel)

        # opportunistic device recapture (utils/recapture.py): a node booted
        # against a dead relay is the best vantage point for an eventual
        # recovery — poll liveness and, on the first recovery, run the
        # device bench suite once and persist the record. Opt-in: a watcher
        # thread per Node would be noise in tests and embedded hosts.
        self.relay_recapture = None
        if os.environ.get("SD_OPPORTUNISTIC_BENCH"):
            if accel is not None:
                want_watcher = not accel.get("devices")
            else:
                # probe disabled: persisted config is stale by definition (a
                # previous boot's relay state) — gate on the sub-second live
                # relay check instead. A listening relay needs no recapture;
                # a dead one is exactly the scenario the watcher exists for.
                from .utils.jax_guard import relay_listening

                want_watcher = not relay_listening()
            if want_watcher:
                from .utils.recapture import RelayRecaptureWatcher

                self.relay_recapture = RelayRecaptureWatcher().start()
                logger.info("no accelerator at boot; watching for relay "
                            "recovery (SD_OPPORTUNISTIC_BENCH)")

        # ordering-critical start sequence (lib.rs:126-130)
        from .jobs import register_builtin_jobs

        register_builtin_jobs()  # JOB_REGISTRY must be full before cold_resume
        self._start_locations()
        self.libraries.init()
        for library in self.libraries.list():
            revived = self.jobs.cold_resume(library)
            if revived:
                logger.info("cold-resumed %d jobs for library %s", revived, library.id[:8])
        self._start_p2p()
        if self.p2p is not None:
            from .server.replica import ReplicaRouter

            self.replica_router = ReplicaRouter.maybe_start(self)

        # dev fixtures (util/debug_initializer.rs:32-56): applied once the
        # managers are live so declared libraries/locations/scans behave
        # exactly like API-driven ones
        from .utils import debug_initializer

        debug_initializer.apply(self)

        # mesh observability (ISSUE 7): bridge the telemetry flight
        # recorder onto this node's event bus (telemetry.watch / SSE tail
        # it from there) and start the SLO/alert evaluator against the
        # process registry. The hook is removed at shutdown — the registry
        # is process-global, and test suites boot many Nodes.
        from . import telemetry
        from .notifications import emit_node_notification
        from .telemetry.alerts import AlertEvaluator

        def _telemetry_event_hook(record: dict,
                                  _emit=self.events.emit_kind) -> None:
            _emit("telemetry.event", record)

        self._telemetry_event_hook = _telemetry_event_hook
        telemetry.add_event_hook(_telemetry_event_hook)

        def _alert_notify(rule, firing: bool, value) -> None:
            if not firing:
                return  # the resolved edge stays in the event ring
            emit_node_notification(self, {
                "type": "alert", "rule": rule.name, "series": rule.series,
                "severity": rule.severity, "value": value,
                "description": rule.description})

        self.alerts = AlertEvaluator(
            interval_s=float(os.environ.get("SD_ALERT_INTERVAL_S", "5")),
            notify=_alert_notify)
        self.alerts.start()

        # SLO engine (ISSUE 20): error-budget + multi-window burn rates
        # over the request/tenant families, narrated as `slo.burn` events
        # next to the alert evaluator's edges. Same ticker discipline.
        from .telemetry.slo import SloEngine

        self.slo = SloEngine(
            interval_s=float(os.environ.get("SD_SLO_INTERVAL_S", "5")))
        self.slo.start()

        # serving-tier observability (ISSUE 10): the process resource
        # watcher always runs (cheap slow ticker — sd_proc_* gauges plus
        # the request-p99 gauges the alert rules read); the span-tagged
        # sampling profiler only when SD_PROFILE_HZ is set (zero overhead
        # when off), exporting its folded stacks at shutdown
        from .telemetry.profiler import ResourceWatcher, SamplingProfiler

        self.resources = ResourceWatcher().start()
        self.profiler = SamplingProfiler().start()

        # device-resident query engine (ISSUE 15): columnar search index
        # scored by batched JAX/Pallas kernels, refreshed at the commit
        # watermark off this node's event bus. Gated: SD_SEARCH_ENGINE=
        # device arms it; default (sqlite) keeps every query on SQL.
        from .search.engine import SearchEngine

        self.search_engine = SearchEngine.maybe_start(self)

        # api::mount last — validates the invalidation-key contract
        # (api/mod.rs:102, invalidate.rs:82)
        from .api.router import mount as api_mount

        self.router = api_mount(self)

    def _start_locations(self) -> None:
        from .locations.manager import LocationsActor

        self.locations = LocationsActor(self)

    def _start_p2p(self) -> None:
        """Start the p2p control plane last in the boot sequence
        (lib.rs:126-130). ``p2p_enabled: false`` in node config (or
        SD_P2P_DISABLED=1) keeps a node offline."""
        import os

        cfg = self.config.get()
        if not cfg.get("p2p_enabled", True) or os.environ.get("SD_P2P_DISABLED"):
            return
        try:
            from .p2p.manager import P2PManager

            self.p2p = P2PManager(self)
            self.p2p.start()
        except Exception:
            logger.exception("p2p failed to start; node stays offline")
            self.p2p = None

    # -- events (lib.rs:203-229) -------------------------------------------
    def emit(self, kind: str, payload: Any = None, library_id: str | None = None) -> None:
        self.events.emit_kind(kind, payload, library_id)

    def shutdown(self) -> None:
        """Graceful: checkpoint all jobs, stop watchers, close DBs
        (Node::shutdown, lib.rs:196)."""
        pool = getattr(self, "reader_pool", None)
        if pool is not None:
            # defensive: the owning shell normally stops it first
            pool.stop()
            self.reader_pool = None
        if getattr(self, "search_engine", None) is not None:
            self.search_engine.stop()
            self.search_engine = None
        self.jobs.shutdown()
        from . import telemetry

        self.alerts.stop()
        self.slo.stop()
        self.resources.stop()
        if self.profiler is not None:
            self.profiler.stop()
            self.profiler.export(self.data_dir)
        telemetry.remove_event_hook(self._telemetry_event_hook)
        if self.relay_recapture is not None:
            self.relay_recapture.stop()
        if self.locations is not None:
            self.locations.stop()
        if self.p2p is not None:
            self.p2p.stop()
        self.thumbnail_remover.stop()
        self.libraries.close()
