"""Sync ingest: receive → arbitrate against the op-log → apply → log → clock.

Role of core/crates/sync/src/ingest.rs (state machine :30-88,
receive_crdt_operation :114-186, per-instance clock persistence :136-159) —
but with a stronger arbitration rule than the reference's ``compare_message``
(:188-233). The reference drops a "stale" op without recording it; that loses
shadow information and lets cross-kind races (create vs update vs delete)
converge differently depending on arrival order. Here the op-log IS the CRDT
state:

- EVERY op is logged (even ones with no materialized effect), so shadow
  information propagates transitively and future arbitration sees the full
  history;
- an op's *effect* is computed against the record's logged history with a
  deterministic (timestamp, op-id) total order — equivalent to replaying the
  record's ops in timestamp order, so every arrival order converges
  (tests/test_sync.py::test_cross_kind_arrival_order_converges proves all
  4! permutations agree):

  * update u:f applies unless a later delete, same-field update, or a later
    create that specifies f exists (per-field LWW);
  * create applies unless a later create/delete exists; fields with later
    updates are stripped, the rest merge into the row;
  * delete with no later create/update removes the row; with later ops it
    takes PARTIAL effect — fields last written before the delete are
    cleared, the row survives (exactly the in-order outcome where the
    delete removes the row and later updates re-materialize it).
"""

from __future__ import annotations

import contextlib
import logging
import queue
import sys
import threading
import time
from typing import TYPE_CHECKING, Any, Callable

from .. import faults, telemetry
from ..models import Instance, RelationOperationRow, SharedOperationRow
from ..telemetry import mesh
from .apply import ApplyError, apply_relation, apply_shared, model_for
from .crdt import CREATE, DELETE, UPDATE_PREFIX, CRDTOperation, RelationOp, SharedOp
from .hlc import to_unix
from .manager import SyncMessage

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

#: transport: clocks, count -> (wire_ops, has_more). Wired to a direct call in
#: tests and to the p2p sync session (GetOpsArgs over the wire) in production.
Transport = Callable[[dict[str, int], int], tuple[list[dict[str, Any]], bool]]

BATCH = 100  # GetOpsArgs.count used by the reference's integration test
#: production pull window: large enough that the batch prefetch and the
#: optimistic single-savepoint pass amortize per-window costs
PROD_BATCH = 1000
#: ops per durable flush when windows are grouped in an ingest session —
#: bounds both the WAL commit cadence and how much a mid-round failure
#: can roll back (everything re-pulls idempotently either way)
SESSION_FLUSH_OPS = 4000

# every ingest family carries a bounded-cardinality ``peer`` label (hash-
# truncated node id via mesh.peer_label, "local" for transport-less
# ingest) — two aggressive peers must be distinguishable in one scrape
_OPS_INGESTED = telemetry.counter(
    "sd_sync_ops_ingested_total", "CRDT ops received for ingest",
    labels=("peer",))
_OPS_APPLIED = telemetry.counter(
    "sd_sync_ops_applied_total",
    "ingested CRDT ops with materialized effect", labels=("peer",))
_WINDOW_SECONDS = telemetry.histogram(
    "sd_sync_window_seconds", "latency of one ingest window",
    labels=("peer",))
_SHED_REPLAYS = telemetry.counter(
    "sd_sync_shed_replays_total",
    "known-poison replays deferred past the per-round fairness cap",
    labels=("peer",))


def _update_field(kind: str) -> str | None:
    return kind[len(UPDATE_PREFIX):] if kind.startswith(UPDATE_PREFIX) else None


class PoisonCaps:
    """Library-wide sticky floor caps for unhealed poison ops (ISSUE 13).

    The per-pass ``poison_cap`` in :meth:`Ingester._ingest_pass` only
    protects the floor inside the window that SAW the poison. Any
    transport that does not immediately re-serve the poisoned op — a
    pipelined session whose cursor ran ahead, a session resuming after a
    partition heal, a *different* peer forwarding later ops from the same
    origin instance — could then advance the instance floor past the
    unapplied op in a later window, losing it forever. This registry
    makes the cap STICKY and library-scoped: every poisoned op holds its
    origin instance's floor below itself across windows, ingesters, and
    lanes until the op durably logs (heal), so the transport keeps
    re-serving it no matter which path delivers the next window.

    Bounded like the per-ingester poison memory: past ``MAX_OPS`` the
    oldest half is evicted — an evicted entry means a still-unhealed op
    loses its floor protection, the same degradation the id-set eviction
    already accepts, and only reachable under an adversarial poison storm.
    """

    MAX_OPS = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: op id -> (origin instance pub_id, op timestamp)
        self._ops: dict[str, tuple[str, int]] = {}

    def add(self, op_id: str, instance: Any, ts: Any) -> None:
        if not isinstance(instance, str) or not isinstance(ts, int):
            return  # unattributable — no floor movement for it at all
        with self._lock:
            self._ops[op_id] = (instance, ts)
            if len(self._ops) > self.MAX_OPS:
                for k in list(self._ops)[: self.MAX_OPS // 2]:
                    del self._ops[k]

    def heal(self, op_id: str) -> bool:
        if not self._ops:  # common case: nothing poisoned, no lock
            return False
        with self._lock:
            return self._ops.pop(op_id, None) is not None

    def floor_caps(self) -> dict[str, int]:
        """Per-instance floor cap (strictly below the oldest unhealed
        poison op of that instance); empty when nothing is poisoned —
        the common case, one lock + len check."""
        with self._lock:
            if not self._ops:
                return {}
            caps: dict[str, int] = {}
            for instance, ts in self._ops.values():
                cap = ts - 1
                if cap < caps.get(instance, cap + 1):
                    caps[instance] = cap
            return caps


def shared_poison_caps(library: "Library") -> PoisonCaps:
    """The library's one sticky-cap registry (every ingester of a library
    shares it — poison in one lane/peer path caps the floor for all)."""
    caps = library.__dict__.get("_sync_poison_caps")
    if caps is None:
        caps = library.__dict__.setdefault("_sync_poison_caps",
                                           PoisonCaps())
    return caps


class Ingester:
    """Synchronous core (usable inline); Actor wraps it in a thread."""

    #: fairness bound on poison REPLAYS per ingest pass: ops that already
    #: failed in a previous round get at most this many re-attempts per
    #: round before the rest are deferred (floor capped, no DB work), so a
    #: hot poisoned record can never starve live ingest of fresh windows
    REPLAY_OPS_PER_ROUND = 64
    #: memory bound on the known-poison id set (oldest half evicted past
    #: this — an evicted id simply counts as fresh again next round)
    POISON_MEMORY = 4096

    def __init__(self, library: "Library", reference_mode: bool = False,
                 peer: str | None = None) -> None:
        self.library = library
        #: identity of the node whose ops this ingester receives (None for
        #: transport-less/test ingest) — attribution only, never auth
        self.peer = peer
        self._peer_label = mesh.peer_label(peer)
        # per-peer series handles memoized off the hot loop
        self._ops_ingested = _OPS_INGESTED.labels(peer=self._peer_label)
        self._ops_applied = _OPS_APPLIED.labels(peer=self._peer_label)
        self._window_seconds = _WINDOW_SECONDS.labels(peer=self._peer_label)
        self._apply_delay = mesh.apply_delay_series(self._peer_label)
        self._shed_replays = _SHED_REPLAYS.labels(peer=self._peer_label)
        self._fresh_ts: list[int] = []
        #: op ids that poisoned in an earlier round (id -> failure count);
        #: replays of these are fairness-capped per round (REPLAY_OPS_PER_
        #: ROUND) and the whole batch skips the optimistic pass (a known
        #: poison would abort it every time — pure wasted savepoint work)
        self._poison_seen: dict[str, int] = {}
        #: library-wide sticky floor caps (shared across every ingester of
        #: this library): an unhealed poison op caps its instance's floor
        #: in EVERY window, not just the one that saw it fail
        self._sticky_caps = shared_poison_caps(library)
        #: lane mode (set by sync/lanes.py): receive() skips floor
        #: persistence and window-level mesh recording, accumulating the
        #: observed clocks/caps for the dispatcher to merge across lanes
        self.deferred_clocks: dict[str, int] = {}
        self.deferred_caps: dict[str, int] = {}
        #: reference-faithful ingestion (benchmark baseline): per-op
        #: arbitration queries and per-op savepoints, exactly the shape of
        #: the reference's receive_crdt_operation loop
        #: (core/crates/sync/src/ingest.rs:114-186) and of this framework
        #: before the batch prefetch landed
        self.reference_mode = reference_mode
        #: whether the last receive() advanced any instance's clock floor —
        #: the single source of truth the pull loops use to detect a stuck
        #: window (a batch whose every op is skipped would otherwise be
        #: re-pulled identically forever)
        self.last_floor_advanced = False
        # per-batch prefetch caches (None outside receive()): the hot loop
        # must not pay one history/dup/instance query PER OP — at 1000-op
        # pull windows that caps ingest near 8k ops/s. receive() loads each
        # record's history, the batch's already-logged ids, and the known
        # instances in a handful of IN-queries, then keeps the caches
        # coherent as ops are logged so intra-batch arbitration still sees
        # every earlier op of the same batch.
        self._shared_hist: dict[tuple[str, str], list[dict[str, Any]]] | None = None
        self._rel_hist: dict[tuple[str, str, str], list[dict[str, Any]]] | None = None
        self._logged_ids: set[str] | None = None
        self._known_instances: set[str] | None = None

    @contextlib.contextmanager
    def session(self):
        """Group several pull windows under ONE durable transaction.

        The per-window overhead that made small windows cost 3× (BENCH_r05:
        30k ops at batch=100 took 3.50s vs 1.17s at batch=1000) is mostly
        the per-receive() BEGIN IMMEDIATE…COMMIT — a WAL commit per window.
        Inside a session the per-window transactions join this outer one
        (models/base._Txn is re-entrant), so the pull loop pays one commit
        per flush instead of one per window. Safe because ingestion is
        idempotent: a mid-session failure rolls the whole flush window back
        and the un-advanced clock floors make the transport replay it.
        """
        with self.library.db.transaction():
            yield
        # serve-pool invalidation (ISSUE 11): the grouped windows are
        # durable NOW — the per-receive() bump below skips itself while a
        # session transaction is open, so this is the one post-commit
        # signal for the whole flush
        if hasattr(self.library, "emit"):
            self.library.emit("db.commit", {"source": "sync.session"})

    # -- history helpers -----------------------------------------------------
    def _history(self, t: SharedOp) -> list[dict[str, Any]]:
        if self._shared_hist is not None:
            key = (t.model, str(t.record_id))
            rows = self._shared_hist.get(key)
            if rows is not None:
                return rows
        return self.library.db.find(
            SharedOperationRow, {"model": t.model, "record_id": str(t.record_id)})

    @staticmethod
    def _later(rows: list[dict[str, Any]], op: CRDTOperation) -> list[dict[str, Any]]:
        """Ops strictly after ``op`` in the (timestamp, id) total order —
        the deterministic cross-instance tiebreak."""
        key = (op.timestamp, op.id)
        return [r for r in rows if (r["timestamp"], r["id"]) > key]

    def _already_logged(self, op: CRDTOperation) -> bool:
        if self._logged_ids is not None:
            return op.id in self._logged_ids
        t = op.typ
        row_model = SharedOperationRow if isinstance(t, SharedOp) else RelationOperationRow
        return self.library.db.find_one(row_model, {"id": op.id}) is not None

    # -- batch prefetch ------------------------------------------------------
    @staticmethod
    def _chunks(items: list, size: int = 400):
        for i in range(0, len(items), size):
            yield items[i : i + size]

    def _prefetch(self, ops: list[CRDTOperation]) -> None:
        db = self.library.db
        shared = [op for op in ops if isinstance(op.typ, SharedOp)]
        rel = [op for op in ops if isinstance(op.typ, RelationOp)]

        logged: set[str] = set()
        for table, group in (("shared_operation", shared),
                             ("relation_operation", rel)):
            for chunk in self._chunks([op.id for op in group]):
                marks = ",".join("?" * len(chunk))
                for r in db.query(
                        f"SELECT id FROM {table} WHERE id IN ({marks})", chunk):
                    logged.add(r["id"])
        self._logged_ids = logged

        shist: dict[tuple[str, str], list[dict[str, Any]]] = {}
        by_model: dict[str, set[str]] = {}
        for op in shared:
            key = (op.typ.model, str(op.typ.record_id))
            shist.setdefault(key, [])
            by_model.setdefault(key[0], set()).add(key[1])
        for model, rids in by_model.items():
            for chunk in self._chunks(sorted(rids)):
                marks = ",".join("?" * len(chunk))
                for r in db.query(
                        "SELECT * FROM shared_operation WHERE model = ? "
                        f"AND record_id IN ({marks})", [model, *chunk]):
                    d = SharedOperationRow.decode_row(r)
                    shist[(model, d["record_id"])].append(d)
        self._shared_hist = shist

        rhist: dict[tuple[str, str, str], list[dict[str, Any]]] = {}
        by_relation: dict[str, set[str]] = {}
        for op in rel:
            key = (op.typ.relation, str(op.typ.item_id), str(op.typ.group_id))
            rhist.setdefault(key, [])
            by_relation.setdefault(key[0], set()).add(key[1])
        for relation, items in by_relation.items():
            for chunk in self._chunks(sorted(items)):
                marks = ",".join("?" * len(chunk))
                for r in db.query(
                        "SELECT * FROM relation_operation WHERE relation = ? "
                        f"AND item_id IN ({marks})", [relation, *chunk]):
                    d = RelationOperationRow.decode_row(r)
                    key = (relation, d["item_id"], d["group_id"])
                    if key in rhist:  # item_id IN over-fetches other groups
                        rhist[key].append(d)
        self._rel_hist = rhist

        self._known_instances = {r["pub_id"] for r in db.find(Instance)}

    def _cache_logged(self, op: CRDTOperation) -> None:
        """Mirror a durably-logged op into the batch caches so later ops of
        the same batch arbitrate against it exactly as a DB re-query would."""
        if self._logged_ids is not None:
            self._logged_ids.add(op.id)
        t = op.typ
        if isinstance(t, SharedOp):
            if self._shared_hist is not None:
                self._shared_hist.setdefault(
                    (t.model, str(t.record_id)), []).append({
                        "id": op.id, "timestamp": op.timestamp,
                        "model": t.model, "record_id": str(t.record_id),
                        "kind": t.kind, "data": t.data,
                    })
        elif self._rel_hist is not None:
            self._rel_hist.setdefault(
                (t.relation, str(t.item_id), str(t.group_id)), []).append({
                    "id": op.id, "timestamp": op.timestamp,
                    "relation": t.relation, "item_id": str(t.item_id),
                    "group_id": str(t.group_id), "kind": t.kind,
                    "data": t.data,
                })

    # -- shared-op arbitration ----------------------------------------------
    def _apply_shared_convergent(self, op: CRDTOperation) -> bool:
        """Apply ``op``'s effect given the record's logged history; returns
        whether anything was materialized."""
        # chaos seam: a crash here during the optimistic pass must roll the
        # batch savepoint back and re-run carefully with per-op isolation
        faults.inject("sync_apply", key=op.id)
        db = self.library.db
        t: SharedOp = op.typ
        history = self._history(t)
        later = self._later(history, op)

        field = _update_field(t.kind)
        if field is not None:
            for r in later:
                if r["kind"] in (DELETE, t.kind):
                    return False
                if r["kind"] == CREATE and isinstance(r["data"], dict) \
                        and field in r["data"]:
                    return False
            apply_shared(db, t)
            return True

        if t.kind == CREATE:
            if not later:  # fast path: nothing can shadow a lone create
                apply_shared(db, t)
                return True
            if any(r["kind"] in (CREATE, DELETE) for r in later):
                return False
            shadowed = {_update_field(r["kind"]) for r in later
                        if r["kind"].startswith(UPDATE_PREFIX)}
            data = {k: v for k, v in (t.data or {}).items() if k not in shadowed}
            apply_shared(db, SharedOp(t.model, t.record_id, CREATE, data))
            return True

        if t.kind == DELETE:
            if any(r["kind"] in (CREATE, DELETE) for r in later):
                return False  # later create revives / later tombstone wins
            survivors = [r for r in later
                         if r["kind"].startswith(UPDATE_PREFIX)
                         or r["kind"] == CREATE]
            if not survivors:
                apply_shared(db, t)
                return True
            # partial effect: the in-order outcome is "delete the row, then
            # later updates re-materialize it" — so clear every field whose
            # last write precedes the delete
            key = (op.timestamp, op.id)
            last: dict[str, tuple[int, str]] = {}
            for r in history:
                rkey = (r["timestamp"], r["id"])
                f = _update_field(r["kind"])
                if f is not None:
                    if rkey > last.get(f, (0, "")):
                        last[f] = rkey
                elif r["kind"] == CREATE and isinstance(r["data"], dict):
                    for cf in r["data"]:
                        if rkey > last.get(cf, (0, "")):
                            last[cf] = rkey
            model = model_for(t.model)
            sync_spec = model.SYNC
            dead = {f: None for f, lk in last.items()
                    if lk < key and f in model.FIELDS and f != sync_spec.id}
            if dead:
                db.update(model, {sync_spec.id: t.record_id}, dead)
            return bool(dead)

        raise ApplyError(f"unknown shared op kind {t.kind!r}")

    # -- relation-op arbitration --------------------------------------------
    def _apply_relation_convergent(self, op: CRDTOperation) -> bool:
        """Relations are link rows (little data, no partial-delete
        reconstruction needed): tombstone-aware kind matrix."""
        faults.inject("sync_apply", key=op.id)
        db = self.library.db
        t: RelationOp = op.typ
        key = (t.relation, str(t.item_id), str(t.group_id))
        rows = self._rel_hist.get(key) if self._rel_hist is not None else None
        if rows is None:
            rows = db.find(RelationOperationRow,
                           {"relation": t.relation, "item_id": str(t.item_id),
                            "group_id": str(t.group_id)})
        later = self._later(rows, op)
        for r in later:
            if r["kind"] == DELETE:
                return False
            if r["kind"] == CREATE and t.kind in (CREATE, DELETE):
                return False
            if r["kind"] == t.kind and t.kind.startswith(UPDATE_PREFIX):
                return False
        apply_relation(db, t)
        return True

    # -- plumbing ------------------------------------------------------------
    def _ensure_instance(self, pub_id: str) -> None:
        """Ops can arrive from an origin we have no instance row for yet
        (transitive propagation ahead of pairing metadata). Create a minimal
        row so logging and clock persistence have a home instead of
        poisoning the batch."""
        import datetime as _dt

        db = self.library.db
        if self._known_instances is not None:
            known = pub_id in self._known_instances
        else:
            known = db.find_one(Instance, {"pub_id": pub_id}) is not None
        if not known:
            now = _dt.datetime.now(_dt.timezone.utc)
            db.insert(Instance, {
                "pub_id": pub_id, "identity": "", "node_id": "",
                "node_name": "(unknown)", "node_platform": 0,
                "last_seen": now, "date_created": now, "timestamp": 0,
            }, or_ignore=True)
            if self._known_instances is not None:
                self._known_instances.add(pub_id)
            logger.warning("sync ingest created placeholder instance %s", pub_id)

    # -- application ---------------------------------------------------------
    def _own_origin(self) -> str:
        """This node's id (span-id base for continued mesh traces)."""
        node = getattr(self.library, "node", None)
        if node is not None:
            try:
                return str(node.config.get().get("id") or self.library.id)
            except Exception:
                pass
        return self.library.id

    def receive(self, wire_ops: list[dict[str, Any]],
                ctx: "mesh.TraceContext | None" = None,
                defer_clocks: bool = False) -> int:
        """Ingest a batch; returns the number of ops with materialized
        effect (shadowed ops are still logged). ``ctx`` is the sender's
        trace-context envelope: when present, this window's apply span
        parents under the sender's serving span (stitched by trace_id)
        and the per-peer convergence-lag gauges update from its HLC
        watermark and declared backlog.

        ``defer_clocks`` is the lane-shard mode (sync/lanes.py): the
        instance clock floors are NOT persisted here — the observed
        clocks and poison caps accumulate into ``deferred_clocks`` /
        ``deferred_caps`` for the dispatcher to merge across every lane
        of the window (a poison in one lane must cap the floor even when
        another lane applied later ops from the same instance) — and the
        window-level mesh/lag recording is left to the dispatcher."""
        db = self.library.db
        sync = self.library.sync
        window_t0 = time.perf_counter()
        self._ops_ingested.inc(len(wire_ops))

        # decode first (one malformed wire op — bad '_t', wrong key set —
        # from a buggy or malicious member must not abort the batch and
        # wedge the sync session forever), so the prefetch sees the batch's
        # full key set
        decoded: list[CRDTOperation] = []
        for wire in wire_ops:
            try:
                decoded.append(CRDTOperation.from_wire(wire))
            except Exception as e:
                logger.warning("sync ingest dropped malformed op: %s", e)

        trace = mesh.continue_trace(ctx, origin=self._own_origin())
        apply_span = mesh.remote_span(trace, ctx, "sync.apply",
                                      peer=self._peer_label,
                                      ops=len(decoded))
        apply_span.__enter__()
        applied = 0
        # timestamps of ops durably LOGGED this window (the passes append)
        # — the apply-delay histogram must not re-count duplicate
        # deliveries or poison-replayed windows as fresh applies
        self._fresh_ts = []

        # NOTE on the raw SAVEPOINTs: db.transaction() holds the connection
        # RLock for the whole batch, so no other thread can interleave
        # statements between a savepoint and its release/rollback — which
        # also keeps the prefetched caches coherent for the whole batch.
        #
        # Two-pass execution: the OPTIMISTIC pass runs the whole batch under
        # a single savepoint with no per-op bookkeeping and the op-log
        # written as one executemany at the end — the happy path pays ~3
        # statements per op instead of 6. Any failure rolls the whole pass
        # back and the CAREFUL pass re-runs it with per-op savepoints and
        # the documented poison/floor semantics. Both passes are
        # deterministic over the same prefetched state, so a clean optimistic
        # pass is bit-identical to what the careful pass would have done.
        # a batch carrying known-poison replays skips the optimistic pass:
        # the poison would abort it deterministically, paying a full batch
        # savepoint rollback before every careful re-run
        has_known_poison = (bool(self._poison_seen)
                            and any(op.id in self._poison_seen
                                    for op in decoded))
        try:
            with db.transaction():
                if self.reference_mode:
                    applied, seen_clocks, caps = self._ingest_pass(
                        decoded, careful=True)
                elif has_known_poison:
                    self._prefetch(decoded)
                    applied, seen_clocks, caps = self._ingest_pass(
                        decoded, careful=True)
                else:
                    self._prefetch(decoded)
                    db.execute("SAVEPOINT ingest_batch")
                    try:
                        applied, seen_clocks, caps = self._ingest_pass(
                            decoded, careful=False)
                        db.execute("RELEASE ingest_batch")
                    except Exception:
                        db.execute("ROLLBACK TO ingest_batch")
                        db.execute("RELEASE ingest_batch")
                        logger.exception("optimistic ingest pass failed; "
                                         "re-running per-op")
                        # the rollback may have deleted placeholder Instance
                        # rows the id-memo already recorded
                        sync._instance_ids.clear()
                        self._prefetch(decoded)  # DB rolled back: rebuild
                        applied, seen_clocks, caps = self._ingest_pass(
                            decoded, careful=True)
                if defer_clocks:
                    # lane mode: accumulate for the dispatcher's cross-lane
                    # merge (floors only-raise; caps only-lower)
                    for pub_id, ts in seen_clocks.items():
                        if ts > self.deferred_clocks.get(pub_id, 0):
                            self.deferred_clocks[pub_id] = ts
                    for pub_id, cap in caps.items():
                        self.deferred_caps[pub_id] = min(
                            self.deferred_caps.get(pub_id, cap), cap)
                else:
                    # persist per-origin clocks (ingest.rs:136-159)
                    self.last_floor_advanced = False
                    for pub_id, ts in seen_clocks.items():
                        row = db.find_one(Instance, {"pub_id": pub_id})
                        if row is not None and (row["timestamp"] or 0) < ts:
                            db.update(Instance, {"pub_id": pub_id},
                                      {"timestamp": ts})
                            self.last_floor_advanced = True
        finally:
            # caches are batch-scoped; standalone method calls stay query-based
            self._shared_hist = self._rel_hist = None
            self._logged_ids = self._known_instances = None
            # the instance-id memo is likewise batch-scoped: a transaction
            # rollback (exception out of the with-block) can delete
            # placeholder Instance rows whose ids were already memoized, and
            # rowids can be recycled — repopulating costs one query per
            # instance per batch
            sync._instance_ids.clear()
            apply_span.set(applied=applied)
            apply_span.__exit__(*sys.exc_info())
        self._ops_applied.inc(applied)
        # serve-pool invalidation (ISSUE 11): bump the read watermark only
        # once the window is DURABLE. Inside a session() the outer
        # transaction is still open here (txn_depth > 0) — the commit
        # lands at session exit, which emits instead; bumping early would
        # let a pool worker cache pre-commit rows under the new watermark
        # and serve them stale after the real commit.
        if db._txn_depth == 0 and hasattr(self.library, "emit"):
            self.library.emit("db.commit", {"source": "sync.ingest",
                                            "ops": len(decoded)})
        # convergence lag + end-to-end delay, from the envelope and the
        # ops' own HLC stamps (per-op observe is a bisect+lock; the window
        # is the unit of everything else). Delay counts only ops durably
        # logged THIS window: duplicates and poison replays are not
        # fresh applies. In lane mode the DISPATCHER records the window
        # (each lane only saw a shard of it).
        if not defer_clocks:
            self._window_seconds.observe(time.perf_counter() - window_t0)
            max_ts = max((op.timestamp for op in decoded), default=0)
            mesh.record_ingest_window(self._peer_label, ctx, max_ts)
        if telemetry.enabled():
            now_unix = time.time()
            for ts in self._fresh_ts:
                self._apply_delay.observe(max(0.0, now_unix - to_unix(ts)))
        self._fresh_ts = []
        if applied:
            sync._broadcast(SyncMessage.INGESTED)
        return applied

    def _ingest_pass(self, decoded: list[CRDTOperation], careful: bool
                     ) -> tuple[int, dict[str, int], dict[str, int]]:
        db = self.library.db
        sync = self.library.sync
        applied = 0
        seen_clocks: dict[str, int] = {}
        pending_log: list[CRDTOperation] = []
        #: replay fairness budget: re-attempts of KNOWN-poison ops this
        #: pass; fresh ops never count against it
        replay_budget = self.REPLAY_OPS_PER_ROUND
        # reset per PASS: an aborted optimistic pass rolls its log rows
        # back, so its entries must not survive into the careful re-run
        self._fresh_ts = []
        # Dropped-op floor policy, by failure class (careful pass):
        #
        # - TRANSIENT failures (savepoint rollback: DB error while logging)
        #   cap the instance's floor below the failed op for the rest of the
        #   batch — ops are timestamp-ordered, so a later successful op from
        #   the same instance would otherwise push the floor past it and it
        #   would never be re-pulled (lost, breaking convergence).
        # - PERMANENT garbage (decode/validation failure) is dropped with no
        #   cap: it can never apply anywhere, and pinning the floor below an
        #   immutable bad op in the origin's log would stall that peer link
        #   forever once more than one window of ops accumulates behind it.
        #   A beyond-drift timestamp sorts after all sane ops anyway, so it
        #   rides the window tail without blocking floor advancement.
        poison_cap: dict[str, int] = {}
        # pass-start snapshot of the library-wide sticky caps: ops that
        # poisoned in EARLIER windows (possibly other lanes/peers) keep
        # holding their instance's floor down even when this window does
        # not contain them — without this, a window of later ops from the
        # same instance would advance the floor past the unapplied poison
        # and it could never be re-served (divergence)
        sticky = self._sticky_caps.floor_caps()

        def _advance(instance: str, ts: int) -> None:
            cap = poison_cap.get(instance)
            if cap is not None:
                ts = min(ts, cap)
            s_cap = sticky.get(instance)
            if s_cap is not None:
                ts = min(ts, s_cap)
            if ts > seen_clocks.get(instance, 0):
                seen_clocks[instance] = ts

        def _poison(instance: Any, ts: Any) -> None:
            if not isinstance(instance, str) or not isinstance(ts, int):
                return  # unattributable — no floor movement for it at all
            cap = min(poison_cap.get(instance, ts - 1), ts - 1)
            poison_cap[instance] = cap
            if seen_clocks.get(instance, 0) > cap:
                seen_clocks[instance] = cap

        for op in decoded:
            if not sync.clock.update(op.timestamp):
                # beyond the drift bound (uhlc parity): deferred, not
                # lost — a skewed-but-honest peer's ops sort after all
                # sane ops, so they ride the window tail without
                # blocking floor advancement and apply once wall time
                # catches up. debug level: this repeats every round for
                # the duration of the skew.
                logger.debug("sync ingest deferred op %s: timestamp %d "
                             "beyond drift bound", op.id, op.timestamp)
                continue
            if op.instance == sync.instance_pub_id:
                continue  # our own op reflected back
            if self._already_logged(op):
                # duplicate delivery — already durable, safe to advance
                # (and if it was ever sticky-poisoned, some path logged it:
                # the cap must lift or the floor would stall forever)
                if op.id in self._poison_seen or sticky:
                    self._poison_seen.pop(op.id, None)
                    if self._sticky_caps.heal(op.id):
                        sticky = self._sticky_caps.floor_caps()
                _advance(op.instance, op.timestamp)
                continue
            if not careful:
                # optimistic: any per-op failure aborts the pass (the caller
                # rolls the batch savepoint back and re-runs carefully)
                if isinstance(op.typ, SharedOp):
                    effect = self._apply_shared_convergent(op)
                else:
                    effect = self._apply_relation_convergent(op)
                self._ensure_instance(op.instance)
                pending_log.append(op)
                self._cache_logged(op)
                self._fresh_ts.append(op.timestamp)
                _advance(op.instance, op.timestamp)
                if effect:
                    applied += 1
                continue
            # replay fairness cap (satellite of ISSUE 8): an op that
            # already poisoned in an earlier round gets a bounded number
            # of re-attempts per round; past the budget it is deferred
            # outright (floor capped as if it failed again, zero DB work)
            # so a hot poisoned record cannot starve the fresh tail of
            # the window
            replayed = op.id in self._poison_seen
            if replayed:
                if replay_budget <= 0:
                    _poison(op.instance, op.timestamp)
                    # re-register the sticky cap (eviction-proofing): the
                    # deferred replay stays floor-protected
                    self._sticky_caps.add(op.id, op.instance, op.timestamp)
                    self._shed_replays.inc()
                    continue
                replay_budget -= 1
            # per-op savepoint: effect + log commit or roll back as a
            # unit — an applied-but-unlogged op would be invisible to
            # future arbitration and never propagate transitively
            db.execute("SAVEPOINT ingest_op")
            try:
                # ANY materialization failure — known (ApplyError) or
                # not (bad data shapes deep in SQL) — is deterministic in
                # the op's content, so retrying can never succeed: roll
                # back just the effect and still log the op, or it would
                # neither propagate transitively nor let the floor
                # advance past it (a permanent wedge). Only failures in
                # the logging infrastructure itself (below) are treated
                # as transient.
                db.execute("SAVEPOINT ingest_effect")
                try:
                    if isinstance(op.typ, SharedOp):
                        effect = self._apply_shared_convergent(op)
                    else:
                        effect = self._apply_relation_convergent(op)
                    db.execute("RELEASE ingest_effect")
                except Exception as e:
                    db.execute("ROLLBACK TO ingest_effect")
                    db.execute("RELEASE ingest_effect")
                    # TRANSIENT classes (sqlite busy, EIO/EINTR) are NOT
                    # deterministic in the op's content — logging such an
                    # op "without effect" would advance the floor past it
                    # and lose the materialization forever (divergence).
                    # Escalate to the poison path instead: floor capped
                    # below the op, replayed next round, applies once the
                    # contention clears. The chaos gate
                    # (sync_apply:sqlite_busy) byte-identity rests on this.
                    from ..utils.retry import is_sqlite_busy, is_transient_io

                    if is_sqlite_busy(e) or is_transient_io(e):
                        raise
                    log = (logger.warning if isinstance(e, ApplyError)
                           else logger.exception)
                    log("sync op %s logged without effect: %s", op.id, e)
                    effect = False
                self._ensure_instance(op.instance)
                sync.log_ops([op])  # ALWAYS — the log is the CRDT state
            except Exception:
                # a single poison op must not abort the whole batch and
                # leave the Actor re-pulling it forever; its clock floor
                # is NOT advanced (and is capped below the poison op for
                # the rest of the batch), so it will be retried next round
                db.execute("ROLLBACK TO ingest_op")
                db.execute("RELEASE ingest_op")
                # the rollback may have deleted a placeholder Instance row
                # this op just created — later ops of the batch must
                # re-create it, not trust the caches
                if self._known_instances is not None:
                    self._known_instances.discard(op.instance)
                sync._instance_ids.pop(op.instance, None)
                _poison(op.instance, op.timestamp)
                self._remember_poison(op.id)
                # sticky: this op holds its instance's floor below itself
                # across FUTURE windows too, until it durably logs
                self._sticky_caps.add(op.id, op.instance, op.timestamp)
                logger.exception("sync ingest skipped poison op %s", op.id)
                continue
            db.execute("RELEASE ingest_op")
            if replayed:
                self._poison_seen.pop(op.id, None)  # healed
                if self._sticky_caps.heal(op.id):
                    sticky = self._sticky_caps.floor_caps()
            self._cache_logged(op)
            self._fresh_ts.append(op.timestamp)
            # advance the clock floor only once the op is durably logged
            _advance(op.instance, op.timestamp)
            if effect:
                applied += 1
        if pending_log:
            sync.log_ops(pending_log)
        # hand the dispatcher the LIVE sticky caps too: in lane mode the
        # poisoned op may sit in a different lane's ingester than the one
        # applying this instance's later ops — the cross-lane floor merge
        # must see the cap regardless of which lane returned it
        for instance, cap in self._sticky_caps.floor_caps().items():
            if cap < poison_cap.get(instance, cap + 1):
                poison_cap[instance] = cap
        return applied, seen_clocks, poison_cap

    def _remember_poison(self, op_id: str) -> None:
        # pop+reinsert so a repeat offender moves to the back of the
        # insertion order: eviction below is then LRU — it drops ids not
        # seen poisoning recently, never the hot still-failing ones the
        # replay cap and optimistic-pass skip exist for
        self._poison_seen[op_id] = self._poison_seen.pop(op_id, 0) + 1
        if len(self._poison_seen) > self.POISON_MEMORY:
            # evict the oldest half (insertion order); an evicted id just
            # counts as fresh on its next replay
            for k in list(self._poison_seen)[: self.POISON_MEMORY // 2]:
                del self._poison_seen[k]


class Actor:
    """Threaded pull loop: ``notify()`` wakes it; it pulls batches from the
    transport until has_more is false, then waits again."""

    def __init__(self, library: "Library", transport: Transport,
                 batch: int = PROD_BATCH) -> None:
        self.ingester = Ingester(library)
        self.library = library
        self.transport = transport
        self.batch = batch
        # wakes COALESCE: one pending wake already guarantees a full pull
        # round, so the queue stays bounded no matter how fast notify()
        # fires (the sdlint queue-discipline invariant)
        self._wake: queue.Queue[object | None] = queue.Queue(maxsize=4)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"sync-ingest-{library.id[:8]}")
        self._stopped = False
        self._thread.start()

    def notify(self) -> None:
        try:
            self._wake.put_nowait(object())
        except queue.Full:
            pass  # a wake is already pending; this one is subsumed

    def stop(self) -> None:
        self._stopped = True
        try:
            self._wake.put_nowait(None)
        except queue.Full:
            pass  # queue non-empty: the loop will see _stopped on next get
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            item = self._wake.get()
            if item is None or self._stopped:
                return
            try:
                done = False
                while not done:
                    # PHASE 1 — network, NO transaction held: pull up to a
                    # flush window's worth of ops, advancing the clocks
                    # locally from the pulled envelopes (the durable floors
                    # only move once ingested, so re-asking the transport
                    # with the same floors would replay the same window)
                    clocks = self.library.sync.timestamps()
                    windows: list[list[dict]] = []
                    pulled = 0
                    while True:
                        ops, has_more = self.transport(clocks, self.batch)
                        if ops:
                            windows.append(ops)
                            pulled += len(ops)
                            for wire in ops:
                                inst, ts = wire.get("instance"), wire.get("timestamp")
                                if isinstance(inst, str) and isinstance(ts, int) \
                                        and ts > clocks.get(inst, 0):
                                    clocks[inst] = ts
                        if not has_more:
                            done = True
                            break
                        if not ops or pulled >= SESSION_FLUSH_OPS:
                            break
                    # PHASE 2 — one durable transaction over the buffered
                    # windows (per-window receive() semantics preserved):
                    # small pull windows no longer pay a WAL commit each
                    # (the 3× batch=100 tax), and the DB lock is never held
                    # across a (possibly remote, possibly hung) transport.
                    # With SD_SYNC_INGEST_LANES > 1 the windows go through
                    # the library's partitioned lane pool instead.
                    if windows:
                        from .lanes import get_lane_pool, lane_count

                        if lane_count() > 1:
                            pool = get_lane_pool(self.library)
                            _, advanced = pool.receive_many(
                                [(ops, None) for ops in windows])
                            self.ingester.last_floor_advanced = advanced
                        else:
                            with self.ingester.session():
                                for ops in windows:
                                    self.ingester.receive(ops)
                        if not self.ingester.last_floor_advanced:
                            # the final window was entirely skipped — the
                            # durable floors did not move, so the transport
                            # would replay the identical window forever
                            logger.warning("ingest made no progress; "
                                           "ending round")
                            done = True
            except Exception:
                logger.exception("sync ingest round failed")
