"""Sync ingest: receive → stale-check → apply → re-log → persist clock.

Mirrors core/crates/sync/src/ingest.rs:

- state machine WaitingForNotification → RetrievingMessages → Ingesting
  (:30-88): a notification triggers pull rounds against a transport callback
  until ``has_more`` is false;
- ``receive_crdt_operation`` (:114-186): update the HLC, drop ops older than
  the newest stored op for the same (model, record, field) target
  ("compare_message" :188-233), apply via the annotation-driven applier,
  re-log the op (transitive propagation + future stale checks), persist the
  origin instance's clock in ``instance.timestamp`` (:136-159).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import TYPE_CHECKING, Any, Callable

from ..models import Instance, RelationOperationRow, SharedOperationRow
from .apply import ApplyError, apply_relation, apply_shared
from .crdt import CREATE, DELETE, UPDATE_PREFIX, CRDTOperation, RelationOp, SharedOp
from .manager import SyncMessage

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

#: transport: clocks, count -> (wire_ops, has_more). Wired to a direct call in
#: tests and to the p2p sync session (GetOpsArgs over the wire) in production.
Transport = Callable[[dict[str, int], int], tuple[list[dict[str, Any]], bool]]

BATCH = 100  # GetOpsArgs.count used by the reference's integration test


class Ingester:
    """Synchronous core (usable inline); Actor wraps it in a thread."""

    def __init__(self, library: "Library") -> None:
        self.library = library

    # -- stale check (compare_message, ingest.rs:188-233) -------------------
    def _is_stale(self, op: CRDTOperation) -> bool:
        db = self.library.db
        t = op.typ
        if isinstance(t, SharedOp):
            rows = db.find(SharedOperationRow,
                           {"model": t.model, "record_id": str(t.record_id)},
                           order_by="timestamp DESC")
        else:
            rows = db.find(RelationOperationRow,
                           {"relation": t.relation, "item_id": str(t.item_id),
                            "group_id": str(t.group_id)},
                           order_by="timestamp DESC")
        for row in rows:
            if row["id"] == op.id:  # already ingested (duplicate delivery)
                return True
            if row["timestamp"] < op.timestamp:
                break  # nothing newer can conflict
            if self._conflicts(op.typ.kind, row["kind"]):
                return True
        return False

    @staticmethod
    def _conflicts(incoming: str, stored: str) -> bool:
        """Does a stored op at >= timestamp shadow the incoming one?
        Per-field LWW: updates conflict only with the same field or a delete;
        creates/deletes conflict with any same-record op."""
        if incoming.startswith(UPDATE_PREFIX):
            return stored == incoming or stored == DELETE
        return True  # CREATE / DELETE are record-level

    # -- application --------------------------------------------------------
    def receive(self, wire_ops: list[dict[str, Any]]) -> int:
        """Apply a batch; returns number of ops actually applied."""
        db = self.library.db
        sync = self.library.sync
        applied = 0
        seen_clocks: dict[str, int] = {}
        with db.transaction():
            for wire in wire_ops:
                op = CRDTOperation.from_wire(wire)
                sync.clock.update(op.timestamp)
                if op.instance == sync.instance_pub_id:
                    continue  # our own op reflected back
                seen_clocks[op.instance] = max(seen_clocks.get(op.instance, 0),
                                               op.timestamp)
                if self._is_stale(op):
                    continue
                try:
                    if isinstance(op.typ, SharedOp):
                        apply_shared(db, op.typ)
                    else:
                        apply_relation(db, op.typ)
                except ApplyError as e:
                    logger.error("sync apply failed for op %s: %s", op.id, e)
                    continue
                sync.log_ops([op])  # re-log under the ORIGIN instance
                applied += 1
            # persist per-origin clocks (ingest.rs:136-159)
            for pub_id, ts in seen_clocks.items():
                row = db.find_one(Instance, {"pub_id": pub_id})
                if row is not None and (row["timestamp"] or 0) < ts:
                    db.update(Instance, {"pub_id": pub_id}, {"timestamp": ts})
        if applied:
            sync._broadcast(SyncMessage.INGESTED)
        return applied


class Actor:
    """Threaded pull loop: ``notify()`` wakes it; it pulls batches from the
    transport until has_more is false, then waits again."""

    def __init__(self, library: "Library", transport: Transport,
                 batch: int = BATCH) -> None:
        self.ingester = Ingester(library)
        self.library = library
        self.transport = transport
        self.batch = batch
        self._wake: queue.Queue[object | None] = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"sync-ingest-{library.id[:8]}")
        self._stopped = False
        self._thread.start()

    def notify(self) -> None:
        self._wake.put(object())

    def stop(self) -> None:
        self._stopped = True
        self._wake.put(None)
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            item = self._wake.get()
            if item is None or self._stopped:
                return
            try:
                while True:
                    clocks = self.library.sync.timestamps()
                    ops, has_more = self.transport(clocks, self.batch)
                    if ops:
                        self.ingester.receive(ops)
                    if not has_more:
                        break
            except Exception:
                logger.exception("sync ingest round failed")
