"""Op application — the ModelSyncData equivalent, driven by annotations.

The reference generates per-model apply code at build time (sd-sync-generator,
crates/sync-generator/src/sync_data.rs: ``ModelSyncData::from_op(...).exec``).
Here the model layer's ``SYNC`` annotations (models/schema.py) carry the same
information, so one generic applier covers every synced model — no codegen.

FK fields arrive as ``ref(table, pub_id)`` markers (crdt.py) and resolve to
local integer ids; a ref whose target row doesn't exist yet resolves to None
for nullable fields (it back-fills when the target's Create op applies and a
later Update rewrites the field) and raises for required ones.
"""

from __future__ import annotations

import logging
from typing import Any

from ..models import MODEL_REGISTRY
from ..models.base import Database, Model, Relation, Shared
from .crdt import CREATE, DELETE, UPDATE_PREFIX, RelationOp, SharedOp, is_ref

logger = logging.getLogger(__name__)


class ApplyError(Exception):
    pass


def model_for(table: str) -> type[Model]:
    try:
        return MODEL_REGISTRY[table]
    except KeyError:
        raise ApplyError(f"unknown synced model {table!r}") from None


def resolve_value(db: Database, value: Any) -> Any:
    if not is_ref(value):
        return value
    table, pub_id = value["__ref__"]
    target = model_for(table)
    sync = target.SYNC
    key = sync.id if isinstance(sync, Shared) else "pub_id"
    row = db.find_one(target, {key: pub_id})
    return row["id"] if row else None


def apply_shared(db: Database, op: SharedOp) -> None:
    model = model_for(op.model)
    sync = model.SYNC
    if not isinstance(sync, Shared):
        raise ApplyError(f"{op.model} is not a Shared model")
    where = {sync.id: op.record_id}

    if op.kind == CREATE:
        fields = {k: resolve_value(db, v) for k, v in (op.data or {}).items()}
        # rowcount-based upsert: one statement in the common (new record)
        # case instead of find_one + insert. OR IGNORE swallows conflicts on
        # ANY unique constraint, so when neither the insert nor the update
        # lands the create was blocked by a foreign unique (e.g. a local
        # file_path row with the same (location, path) but another pub_id) —
        # surface that as ApplyError so the op is logged without effect and
        # the divergence stays visible, as the plain-INSERT path did.
        if not db.insert_ignore(model, {**where, **fields}):
            updated = db.update(model, where, fields) if fields else None
            if updated == 0 or (updated is None
                                and db.find_one(model, where) is None):
                raise ApplyError(
                    f"create for {op.model} {op.record_id!r} blocked by a "
                    "unique constraint on another record")
    elif op.kind == DELETE:
        db.delete(model, where)
    elif op.kind.startswith(UPDATE_PREFIX):
        field = op.kind[len(UPDATE_PREFIX):]
        if field not in model.FIELDS:
            raise ApplyError(f"{op.model} has no field {field!r}")
        value = resolve_value(db, op.data)
        if db.update(model, where, {field: value}) == 0:
            # update for a record we never saw: materialize it (the reference
            # applies ops idempotently; order across instances isn't
            # guaranteed)
            if not db.insert_ignore(model, {**where, field: value}):
                raise ApplyError(
                    f"update for {op.model} {op.record_id!r} blocked by a "
                    "unique constraint on another record")
    else:
        raise ApplyError(f"unknown shared op kind {op.kind!r}")


def apply_relation(db: Database, op: RelationOp) -> None:
    model = model_for(op.relation)
    sync = model.SYNC
    if not isinstance(sync, Relation):
        raise ApplyError(f"{op.relation} is not a Relation model")

    item_model = model_for(sync.item)
    group_model = model_for(sync.group)
    item = db.find_one(item_model, {_shared_key(item_model): op.item_id})
    group = db.find_one(group_model, {_shared_key(group_model): op.group_id})
    if item is None or group is None:
        # link precedes its endpoints; the reference drops these too (the
        # endpoint's own Create op re-links via a later relation op replay)
        logger.warning("relation %s op %s: missing endpoint (item=%s group=%s)",
                       op.relation, op.kind, op.item_id, op.group_id)
        return
    where = {f"{sync.item}_id": item["id"], f"{sync.group}_id": group["id"]}

    if op.kind == CREATE:
        fields = {k: resolve_value(db, v) for k, v in (op.data or {}).items()}
        if db.find_one(model, where) is None:
            db.insert(model, {**where, **fields})
        elif fields:
            db.update(model, where, fields)
    elif op.kind == DELETE:
        db.delete(model, where)
    elif op.kind.startswith(UPDATE_PREFIX):
        field = op.kind[len(UPDATE_PREFIX):]
        db.upsert(model, where, {field: resolve_value(db, op.data)},
                  {field: resolve_value(db, op.data)})
    else:
        raise ApplyError(f"unknown relation op kind {op.kind!r}")


def _shared_key(model: type[Model]) -> str:
    return model.SYNC.id if isinstance(model.SYNC, Shared) else "pub_id"
