"""Sync manager stub — fleshed out by the sync layer milestone.

Interface shape follows core/crates/sync/src/manager.rs: domain writes go
through ``write_ops`` so CRDT operations are logged atomically with the data
mutation when message emission is on.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..library import Library


class SyncManager:
    def __init__(self, library: "Library") -> None:
        self.library = library
        self.emit_messages = False  # BackendFeature.SYNC_EMIT_MESSAGES gates this
