"""Sync manager: atomic op emission + ordered op serving.

Follows core/crates/sync/src/manager.rs semantics:

- ``write_ops(ops, fn)`` — run the domain mutation and append the CRDT ops to
  the op-log in ONE SQLite transaction (manager.rs:62-99), then broadcast
  ``SyncMessage.CREATED``. When ``emit_messages`` is off the mutation runs
  bare (no log rows) — same flag-gating as the reference's
  ``emit_messages_flag``.
- ``get_ops(clocks, count)`` — merged shared+relation fetch, timestamp-
  ordered, newer than the caller's per-instance HLC clocks (manager.rs:130-199).
- factories (``shared_create`` etc.) — the OperationFactory equivalent
  (crates/sync/src/factory.rs), stamping (instance pub_id, HLC now, uuid).
"""

from __future__ import annotations

import logging
import threading
from typing import TYPE_CHECKING, Any, Callable

from ..models import Instance
from .crdt import (CREATE, DELETE, UPDATE_PREFIX, CRDTOperation, RelationOp,
                   SharedOp, new_op)
from .hlc import HLC

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)


class SyncMessage:
    CREATED = "created"     # this instance logged new ops
    INGESTED = "ingested"   # remote ops were applied here


class SyncManager:
    def __init__(self, library: "Library") -> None:
        self.library = library
        self.emit_messages = False  # BackendFeature.SYNC_EMIT_MESSAGES gates this
        self.clock = HLC(self._stored_clock_floor())
        self._subscribers: list[Callable[[str], None]] = []
        self._lock = threading.Lock()
        self._instance_ids: dict[str, int] = {}

    # -- identity -----------------------------------------------------------
    @property
    def instance_pub_id(self) -> str:
        # memoized: the library's own instance pub_id is immutable, and the
        # ingest loop consults this once per op
        cached = self.__dict__.get("_own_pub_id")
        if cached is not None:
            return cached
        row = self.library.instance()
        if row is None:
            raise RuntimeError("library has no instance row")
        self.__dict__["_own_pub_id"] = row["pub_id"]
        return row["pub_id"]

    def _instance_db_id(self, pub_id: str) -> int:
        # memoized: log_ops resolves this per op and instance rows are
        # append-only (never re-keyed), so the mapping cannot go stale
        cached = self._instance_ids.get(pub_id)
        if cached is not None:
            return cached
        row = self.library.db.find_one(Instance, {"pub_id": pub_id})
        if row is None:
            raise RuntimeError(f"unknown instance {pub_id}")
        self._instance_ids[pub_id] = row["id"]
        return row["id"]

    def _stored_clock_floor(self) -> int:
        """Resume the HLC past everything already logged (restart safety)."""
        try:
            row = self.library.db.query(
                "SELECT max(m) AS m FROM (SELECT max(timestamp) m FROM shared_operation "
                "UNION ALL SELECT max(timestamp) m FROM relation_operation)")
            return row[0]["m"] or 0
        except Exception:
            return 0

    # -- subscriptions ------------------------------------------------------
    def subscribe(self, fn: Callable[[str], None]) -> None:
        """fn(SyncMessage.*) — NLM push-notify + UI sync.newMessage feed."""
        with self._lock:
            self._subscribers.append(fn)

    def _broadcast(self, message: str) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(message)
            except Exception:
                logger.exception("sync subscriber failed")
        self.library.emit("sync.newMessage", {"kind": message})

    # -- op factories (factory.rs) -----------------------------------------
    @staticmethod
    def _table(model: Any) -> str:
        return getattr(model, "TABLE", model)

    def shared_create(self, model: Any, record_id: Any,
                      fields: dict[str, Any] | None = None) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      SharedOp(self._table(model), record_id, CREATE, fields or {}))

    def shared_update(self, model: Any, record_id: Any, field: str,
                      value: Any) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      SharedOp(self._table(model), record_id, UPDATE_PREFIX + field, value))

    def shared_delete(self, model: Any, record_id: Any) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      SharedOp(self._table(model), record_id, DELETE, None))

    def relation_create(self, relation: Any, item_id: Any, group_id: Any,
                        fields: dict[str, Any] | None = None) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      RelationOp(self._table(relation), item_id, group_id,
                                 CREATE, fields or {}))

    def relation_update(self, relation: Any, item_id: Any, group_id: Any,
                        field: str, value: Any) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      RelationOp(self._table(relation), item_id, group_id,
                                 UPDATE_PREFIX + field, value))

    def relation_delete(self, relation: Any, item_id: Any, group_id: Any) -> CRDTOperation:
        return new_op(self.instance_pub_id, self.clock.now(),
                      RelationOp(self._table(relation), item_id, group_id, DELETE, None))

    def created(self) -> None:
        """Post-commit notification hook for call sites that logged ops inside
        their own transaction (the broadcast must happen after commit)."""
        self._broadcast(SyncMessage.CREATED)

    def shared_create_many(self, model: Any, rows: list[dict[str, Any]],
                           log: bool = True) -> list[CRDTOperation]:
        """Bulk create-ops from model rows (the indexer save path). Local
        integer FKs to synced models are rewritten as ``ref`` markers via the
        target's sync id; local-only fields (id, SYNC_SKIP) are dropped;
        datetimes become ISO strings (wire is JSON-safe)."""
        import datetime as _dt

        from ..models import MODEL_REGISTRY
        from .crdt import ref

        spec = model.SYNC
        db = self.library.db
        ref_cache: dict[tuple[str, Any], Any] = {}
        skip = set(getattr(model, "SYNC_SKIP", ())) | {"id", spec.id}
        ops: list[CRDTOperation] = []
        for row in rows:
            fields: dict[str, Any] = {}
            for name, f in model.FIELDS.items():
                if name in skip or name not in row or row[name] is None:
                    continue
                v = row[name]
                if f.references:
                    table = f.references.split(".")[0]
                    target = MODEL_REGISTRY.get(table)
                    # FK crosses the wire as the target's sync id / pub_id
                    # (even @local models like instance have replicated
                    # pub_ids via pairing); targets without one are dropped
                    tkey = (target.SYNC.id if target is not None and target.SYNC
                            else "pub_id" if target is not None and "pub_id" in target.FIELDS
                            else None)
                    if target is None or tkey is None:
                        continue
                    key = (table, v)
                    if key not in ref_cache:
                        trow = db.find_one(target, {"id": v})
                        ref_cache[key] = trow[tkey] if trow else None
                    if ref_cache[key] is None:
                        continue
                    v = ref(table, ref_cache[key])
                if isinstance(v, _dt.datetime):
                    v = v.isoformat()
                fields[name] = v
            ops.append(self.shared_create(model, row[spec.id], fields))
        if log:
            self.log_ops(ops)
        return ops

    # -- write path ---------------------------------------------------------
    def write_ops(self, ops: list[CRDTOperation],
                  fn: Callable[[Any], Any] | None = None) -> Any:
        """Atomically run ``fn(db)`` and append ``ops`` to the op-log; no-op
        logging (mutation only) when emit_messages is off."""
        db = self.library.db
        result = None
        with db.transaction():
            if fn is not None:
                result = fn(db)
            if self.emit_messages and ops:
                self.log_ops(ops)
        if self.emit_messages and ops:
            self._broadcast(SyncMessage.CREATED)
        return result

    def log_ops(self, ops: list[CRDTOperation]) -> None:
        import json as _json

        db = self.library.db
        shared_rows: list[tuple] = []
        relation_rows: list[tuple] = []
        for op in ops:
            inst = self._instance_db_id(op.instance)
            t = op.typ
            data = (None if t.data is None
                    else _json.dumps(t.data, sort_keys=True))
            if isinstance(t, SharedOp):
                shared_rows.append((op.id, op.timestamp, t.model,
                                    str(t.record_id), t.kind, data, inst))
            else:
                relation_rows.append((op.id, op.timestamp, t.relation,
                                      str(t.item_id), str(t.group_id),
                                      t.kind, data, inst))
        # one pre-encoded executemany per table: the ingest fast path logs
        # whole pull windows at once
        if shared_rows:
            db.executemany(
                "INSERT OR IGNORE INTO shared_operation "
                "(id, timestamp, model, record_id, kind, data, instance_id) "
                "VALUES (?, ?, ?, ?, ?, ?, ?)", shared_rows)
        if relation_rows:
            db.executemany(
                "INSERT OR IGNORE INTO relation_operation "
                "(id, timestamp, relation, item_id, group_id, kind, data, "
                "instance_id) VALUES (?, ?, ?, ?, ?, ?, ?, ?)", relation_rows)

    # -- read path ----------------------------------------------------------
    def timestamps(self) -> dict[str, int]:
        """Per-origin-instance applied-clock map (GetOpsArgs.clocks). For our
        own instance: everything we logged; for peers: instance.timestamp as
        persisted by ingest (ingest.rs:136-159)."""
        out: dict[str, int] = {}
        for row in self.library.db.find(Instance):
            if row["id"] == self.library.instance_id:
                out[row["pub_id"]] = self.clock.last
            else:
                out[row["pub_id"]] = row["timestamp"] or 0
        return out

    def require_watermark(self) -> dict[str, int]:
        """Per-publisher floors a replica must cover to serve THIS node's
        reads (the ``require`` map of server/replica.py's ``covers``).
        Built from the op-LOG, not :meth:`timestamps`, for two reasons:

        - our own entry there is ``clock.last``, and the HLC merges
          forward on every ingested remote op — a node that mostly
          consumes runs its clock ahead of any op a peer could ever
          replicate, so the raw clock map reads as permanently
          uncoverable (NOT_ELIGIBLE forever) even at full convergence;
        - peer entries there are ``instance.timestamp``, which lane-mode
          ingest persists only at the dispatcher's deferred cross-lane
          merge — mid-window they UNDERSTATE what is already
          materialized, and an understated require admits stale pages.

        Ops are logged in the same transaction that materializes them, so
        ``max(timestamp)`` per origin instance over both log tables is
        exactly the applied floor — and exactly the sup of what a replica
        can pull from us, coupling eligibility to byte-equal state.
        ``instance.timestamp`` is max-merged in for floors whose log
        entries a future compaction might drop."""
        db = self.library.db
        out: dict[str, int] = {}
        id_to_pub: dict[Any, str] = {}
        for row in db.find(Instance):
            id_to_pub[row["id"]] = row["pub_id"]
            mine = row["id"] == self.library.instance_id
            out[row["pub_id"]] = 0 if mine else (row["timestamp"] or 0)
        for table in ("shared_operation", "relation_operation"):
            for r in db.query(
                    f"SELECT instance_id, max(timestamp) AS t FROM {table} "
                    "GROUP BY instance_id"):
                pub = id_to_pub.get(r["instance_id"])
                if pub is not None and (r["t"] or 0) > out.get(pub, 0):
                    out[pub] = r["t"]
        return out

    def ops_pending(self, clocks: dict[str, int] | None = None) -> int:
        """How many logged ops are strictly newer (per origin instance)
        than ``clocks`` — the sender-side backlog count a sync window's
        trace-context envelope declares so the RECEIVER can publish its
        own convergence lag (``sd_sync_peer_lag_ops``) without a second
        round trip. One COUNT per instance per table, each an indexed
        range SEARCH on (instance_id, timestamp) — a CASE-over-instance_id
        form would degrade to a full index scan of the whole op-log on
        every served window."""
        clocks = clocks or {}
        db = self.library.db
        total = 0
        for r in db.find(Instance):
            floor = clocks.get(r["pub_id"], 0)
            for table in ("shared_operation", "relation_operation"):
                total += db.query(
                    f"SELECT count(*) AS c FROM {table} "
                    "WHERE instance_id = ? AND timestamp > ?",
                    [r["id"], floor])[0]["c"]
        return total

    def get_ops(self, clocks: dict[str, int] | None = None,
                count: int = 100) -> tuple[list[dict[str, Any]], bool]:
        """Ops strictly newer (per origin instance) than ``clocks``, merged
        across both log tables in timestamp order. Returns (wire_ops,
        has_more).

        The per-instance floor, ordering, and LIMIT run in SQL (each table
        contributes at most count+1 rows per round), so a full sync is
        O(count log count) per round instead of loading the whole op-log.

        Relayed ops (origin != our instance) are served only up to the
        floor WE have durably persisted for their origin: lane-mode
        ingest commits each lane's shard before the dispatcher's
        cross-lane floor merge, so the raw log can briefly hold a later
        op from an origin while an earlier one is still in another
        lane's transaction — a puller that read past the merge point
        would advance its scalar clock over the hole and never fetch the
        backfilled op. Serial ingest persists floors in the same
        transaction that logs the window, so the cap is invisible there;
        our own authored ops are append-ordered and need no cap."""
        clocks = clocks or {}
        db = self.library.db
        inst_rows = db.find(Instance)
        inst_pub: dict[int, str] = {r["id"]: r["pub_id"] for r in inst_rows}

        # timestamp > (per-instance clock floor, 0 for unknown instances)
        case_parts: list[str] = []
        case_params: list[Any] = []
        cap_parts: list[str] = []
        cap_params: list[Any] = []
        for r in inst_rows:
            floor = clocks.get(r["pub_id"], 0)
            if floor:
                case_parts.append("WHEN ? THEN ?")
                case_params.extend([r["id"], floor])
            if r["id"] != self.library.instance_id:
                cap_parts.append("WHEN ? THEN ?")
                cap_params.extend([r["id"], r["timestamp"] or 0])
        floor_sql = (f"CASE instance_id {' '.join(case_parts)} ELSE 0 END"
                     if case_parts else "0")
        no_cap = (1 << 63) - 1
        cap_sql = (f"CASE instance_id {' '.join(cap_parts)} "
                   f"ELSE {no_cap} END" if cap_parts else str(no_cap))

        import json as _json

        def fetch(table: str) -> list:
            return db.query(
                f"SELECT * FROM {table} WHERE timestamp > {floor_sql} "
                f"AND timestamp <= {cap_sql} "
                f"ORDER BY timestamp, id LIMIT ?",
                case_params + cap_params + [count + 1])

        # wire dicts built straight from the rows (no dataclass round-trip:
        # this is the sender-side hot loop of big pull windows)
        def _data(v: Any) -> Any:
            return _json.loads(v) if isinstance(v, str) else v

        ops: list[dict[str, Any]] = []
        for r in fetch("shared_operation"):
            ops.append({
                "instance": inst_pub[r["instance_id"]],
                "timestamp": r["timestamp"], "id": r["id"],
                "typ": {"model": r["model"], "record_id": r["record_id"],
                        "kind": r["kind"], "data": _data(r["data"]),
                        "_t": "shared"}})
        for r in fetch("relation_operation"):
            ops.append({
                "instance": inst_pub[r["instance_id"]],
                "timestamp": r["timestamp"], "id": r["id"],
                "typ": {"relation": r["relation"], "item_id": r["item_id"],
                        "group_id": r["group_id"], "kind": r["kind"],
                        "data": _data(r["data"]), "_t": "relation"}})
        ops.sort(key=lambda o: (o["timestamp"], o["id"]))
        has_more = len(ops) > count
        return ops[:count], has_more
