"""Admission control: shed, don't buffer — for sync ingest AND rspc dispatch.

Before this module the ingest side accepted every window a peer handed it
and queued the work behind one serialized lane — overload meant unbounded
memory growth and lag that never drains. The LSHBloom discipline (PAPERS.md,
arxiv 2411.04257) applies verbatim to streaming ingest: keep a HARD bound
on in-flight state and degrade explicitly when it is hit.

:class:`IngestBudget` tracks ops and bytes admitted-but-not-yet-durable
across every ingest source of one node. ``try_admit`` either returns an
:class:`Admission` token (``release()`` it when the window is durable) or a
:class:`Busy` verdict carrying ``retry_after_ms`` — the responder answers
the peer with an explicit BUSY frame instead of buffering the window, and
the originator backs off and resumes from its acknowledged watermark
(p2p/nlm.py; docs/architecture/robustness.md "Overload & admission
control").

Fairness: the budget is shared, but a peer with NOTHING in flight that
asks for less than its fair share (budget ÷ peers currently in flight) is
never shed — only the hard global bound sheds a peer that already holds
in-flight work, even an under-share one. A flooding peer therefore absorbs
the shedding while well-behaved peers keep draining — the per-peer
fairness gate in tests/test_fleet.py rests on this.

The ``sync_ingest`` fault seam lives at the admission check: an armed
``sync_ingest:overload`` rule sheds windows exactly as a real over-budget
node would, which is how the fleet chaos soak exercises the whole
BUSY/backoff/resume loop deterministically.

ISSUE 20 extends the same shape to the SERVING tier:
:class:`DispatchBudget` bounds concurrent rspc dispatches per node,
keyed by **tenant** (the bounded library-id hash from
``telemetry/slo.py tenant_label``) instead of peer. Identical fairness
algebra — a tenant under its fair share (budget ÷ tenants in flight)
with nothing in flight is never shed, so a flooding tenant absorbs the
shedding while quiet tenants keep their latency — and an identical
pressure-scaled ``retry_after_ms``. The router turns a
:class:`Busy` verdict into a ``BusyError`` (HTTP 429) which request
telemetry classifies as outcome ``shed``, excluded from SLO error
ratios: admission control is load management, not an outage. The
``rspc_admission`` fault seam sheds dispatches deterministically for
chaos runs, mirroring ``sync_ingest``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from .. import faults, telemetry
from ..utils.locks import SdLock

#: default ops admitted-but-not-yet-durable across all peers (≈ four
#: production pull windows); bytes default sized for JSON-framed windows
DEFAULT_BUDGET_OPS = int(os.environ.get("SD_SYNC_INGEST_BUDGET_OPS", "4000"))
DEFAULT_BUDGET_BYTES = int(os.environ.get("SD_SYNC_INGEST_BUDGET_BYTES",
                                          str(32 * 1024 * 1024)))
#: what a shed peer is told to wait before resuming (ms); scaled up with
#: how far over budget the node is
BASE_RETRY_AFTER_MS = int(os.environ.get("SD_SYNC_RETRY_AFTER_MS", "200"))

#: default max concurrent rspc dispatches (DispatchBudget); generous —
#: the point is bounding queue collapse under open-loop overload, not
#: throttling healthy traffic
DEFAULT_DISPATCH_INFLIGHT = 64
#: what a shed rspc client is told to wait (ms), scaled by pressure
BASE_DISPATCH_RETRY_AFTER_MS = 50

_SHED_WINDOWS = telemetry.counter(
    "sd_sync_shed_windows_total",
    "ingest windows answered BUSY instead of buffered", labels=("peer",))
_SHED_OPS = telemetry.counter(
    "sd_sync_shed_ops_total",
    "CRDT ops shed by admission control (re-served after backoff)",
    labels=("peer",))
_ADMIT_OPS = telemetry.gauge(
    "sd_sync_admission_ops_in_flight",
    "CRDT ops admitted but not yet durable")
_ADMIT_BYTES = telemetry.gauge(
    "sd_sync_admission_bytes_in_flight",
    "window bytes admitted but not yet durable")
_BUDGET_OPS = telemetry.gauge(
    "sd_sync_admission_budget_ops", "configured ingest budget (ops)")
_BUDGET_BYTES = telemetry.gauge(
    "sd_sync_admission_budget_bytes", "configured ingest budget (bytes)")
# dispatch-admission families (help text lives in _declare_core)
_D_SHED = telemetry.counter("sd_rspc_shed_total", labels=("tenant",))
_D_INFLIGHT = telemetry.gauge("sd_rspc_admission_in_flight")
_D_BUDGET = telemetry.gauge("sd_rspc_admission_budget")


@dataclass(frozen=True)
class Busy:
    """The shed verdict: tell the peer when to come back. ``watermark`` is
    filled in by the session layer (the receiver's durable clocks — the
    acknowledgment the originator resumes from)."""

    retry_after_ms: int
    reason: str = "over budget"


class Admission:
    """Token for one admitted window; ``release()`` exactly once when the
    window's ops are durable (or abandoned)."""

    __slots__ = ("_budget", "_peer", "_ops", "_bytes", "_released")

    def __init__(self, budget: "IngestBudget", peer: str, ops: int,
                 nbytes: int) -> None:
        self._budget = budget
        self._peer = peer
        self._ops = ops
        self._bytes = nbytes
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._budget._release(self._peer, self._ops, self._bytes)

    def __enter__(self) -> "Admission":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class IngestBudget:
    """Bounded (ops, bytes) in flight across every ingest source of a node.

    Thread-safe; the p2p responder, the pull Actor's remote path, and the
    fleet harness's wire-less sessions all admit through one instance per
    node (``Node.ingest_budget``)."""

    def __init__(self, max_ops: int = DEFAULT_BUDGET_OPS,
                 max_bytes: int = DEFAULT_BUDGET_BYTES) -> None:
        self.max_ops = max(1, int(max_ops))
        self.max_bytes = max(1, int(max_bytes))
        # non-reentrant by design: _shed_locked exists precisely because
        # re-acquiring this lock from a helper WAS the PR 8 self-deadlock
        self._lock = SdLock("sync.admission.budget")
        self._ops = 0
        self._bytes = 0
        #: peer label -> (ops, bytes) currently in flight
        self._per_peer: dict[str, tuple[int, int]] = {}
        self._shed_windows = 0
        self._shed_ops = 0
        _BUDGET_OPS.set(self.max_ops)
        _BUDGET_BYTES.set(self.max_bytes)

    # -- admission -----------------------------------------------------------
    def try_admit(self, peer: str, ops: int,
                  nbytes: int = 0) -> Admission | Busy:
        """Admit ``ops``/``nbytes`` for ``peer`` or return a Busy verdict.
        A window larger than the whole budget is still admitted when the
        node is otherwise idle (a peer must always be able to make
        progress; the bound is on BUFFERED work, not window size)."""
        try:
            # chaos seam: an armed sync_ingest rule sheds this window (any
            # raising kind — `overload` is the canonical one)
            faults.inject("sync_ingest", key=peer)
        except Exception:
            return self._shed(peer, ops, "injected overload")
        with self._lock:
            p_ops, p_bytes = self._per_peer.get(peer, (0, 0))
            active = len(self._per_peer) + (0 if peer in self._per_peer
                                            else 1)
            over_global = (self._ops + ops > self.max_ops
                           or self._bytes + nbytes > self.max_bytes)
            if over_global and self._ops == 0 and self._bytes == 0:
                over_global = False  # idle node: oversized windows admit
            # fairness floor: a peer under its fair share (ops AND bytes)
            # is only shed by the hard global bound when it ALREADY holds
            # in-flight work — so total in-flight can overshoot the budget
            # by at most one sub-share window per fresh source
            fair_ops = self.max_ops // max(1, active)
            fair_bytes = self.max_bytes // max(1, active)
            under_share = (p_ops + ops <= max(fair_ops, 1)
                           and p_bytes + nbytes <= max(fair_bytes, 1))
            if over_global and (not under_share or p_ops > 0):
                pressure = self._shed_locked(ops)
            else:
                self._ops += ops
                self._bytes += nbytes
                self._per_peer[peer] = (p_ops + ops, p_bytes + nbytes)
                pressure = None
        if pressure is not None:
            return self._busy(peer, ops, pressure, "over budget")
        self._publish()
        return Admission(self, peer, ops, nbytes)

    def _shed_locked(self, ops: int) -> float:
        """Shed bookkeeping (callers hold the lock); returns the pressure
        factor scaling the advised backoff so a storm of shed peers
        decorrelates instead of re-dialing in lockstep."""
        self._shed_windows += 1
        self._shed_ops += ops
        return max(1.0, self._ops / self.max_ops)

    def _shed(self, peer: str, ops: int, reason: str) -> Busy:
        with self._lock:
            pressure = self._shed_locked(ops)
        return self._busy(peer, ops, pressure, reason)

    def _busy(self, peer: str, ops: int, pressure: float,
              reason: str) -> Busy:
        _SHED_WINDOWS.inc(peer=peer)
        _SHED_OPS.inc(ops, peer=peer)
        telemetry.event("sync.shed", peer=peer, ops=ops, reason=reason)
        return Busy(retry_after_ms=int(BASE_RETRY_AFTER_MS * pressure),
                    reason=reason)

    def _release(self, peer: str, ops: int, nbytes: int) -> None:
        with self._lock:
            self._ops = max(0, self._ops - ops)
            self._bytes = max(0, self._bytes - nbytes)
            p_ops, p_bytes = self._per_peer.get(peer, (0, 0))
            p_ops, p_bytes = max(0, p_ops - ops), max(0, p_bytes - nbytes)
            if p_ops == 0 and p_bytes == 0:
                self._per_peer.pop(peer, None)
            else:
                self._per_peer[peer] = (p_ops, p_bytes)
        self._publish()

    def _publish(self) -> None:
        with self._lock:
            ops, nbytes = self._ops, self._bytes
        _ADMIT_OPS.set(ops)
        _ADMIT_BYTES.set(nbytes)

    # -- introspection (the fleet status surface) ----------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "budget_ops": self.max_ops,
                "budget_bytes": self.max_bytes,
                "ops_in_flight": self._ops,
                "bytes_in_flight": self._bytes,
                "peers_in_flight": len(self._per_peer),
                "shed_windows": self._shed_windows,
                "shed_ops": self._shed_ops,
            }


class DispatchBudget:
    """The IngestBudget shape at the rspc dispatch seam (ISSUE 20):
    bounded CONCURRENT dispatches per node, keyed by tenant.

    One unit of budget = one in-flight dispatch (``ops=1`` on the shared
    :class:`Admission` token). Fairness is IngestBudget's verbatim: a
    tenant under its fair share (budget ÷ tenants in flight) with
    nothing in flight is never shed — only the hard global bound sheds a
    tenant that already holds in-flight work. ``Node.dispatch_budget``
    holds one instance; the router admits every non-telemetry dispatch
    through it (telemetry.* stays exempt — observability must survive
    the overload it exists to narrate)."""

    def __init__(self, max_inflight: int | None = None) -> None:
        if max_inflight is None:
            # read at construction, not import: bench/tests retune via
            # env between Node boots (the ReaderPool knob pattern)
            try:
                max_inflight = int(os.environ.get(
                    "SD_RSPC_BUDGET", str(DEFAULT_DISPATCH_INFLIGHT)))
            except ValueError:
                max_inflight = DEFAULT_DISPATCH_INFLIGHT
        self.max_inflight = max(1, int(max_inflight))
        try:
            self.base_retry_after_ms = int(os.environ.get(
                "SD_RSPC_RETRY_AFTER_MS",
                str(BASE_DISPATCH_RETRY_AFTER_MS)))
        except ValueError:
            self.base_retry_after_ms = BASE_DISPATCH_RETRY_AFTER_MS
        self._lock = SdLock("api.admission.budget")
        self._inflight = 0
        #: tenant label -> dispatches currently in flight
        self._per_tenant: dict[str, int] = {}
        self._shed = 0
        _D_BUDGET.set(self.max_inflight)

    # -- admission -----------------------------------------------------------
    def try_admit(self, tenant: str) -> Admission | Busy:
        """Admit one dispatch for ``tenant`` or return a Busy verdict."""
        try:
            # chaos seam: an armed rspc_admission rule sheds this dispatch
            # exactly as a real over-budget node would
            faults.inject("rspc_admission", key=tenant)
        except Exception:
            with self._lock:
                pressure = self._shed_locked()
            return self._busy(tenant, pressure, "injected overload")
        with self._lock:
            t_inflight = self._per_tenant.get(tenant, 0)
            active = len(self._per_tenant) + (0 if tenant in self._per_tenant
                                              else 1)
            over_global = self._inflight + 1 > self.max_inflight
            fair = self.max_inflight // max(1, active)
            under_share = t_inflight + 1 <= max(fair, 1)
            if over_global and (not under_share or t_inflight > 0):
                pressure = self._shed_locked()
                inflight = self._inflight
            else:
                self._inflight += 1
                self._per_tenant[tenant] = t_inflight + 1
                pressure = None
                inflight = self._inflight
        if pressure is not None:
            return self._busy(tenant, pressure, "over budget")
        _D_INFLIGHT.set(inflight)
        return Admission(self, tenant, 1, 0)

    def _shed_locked(self) -> float:
        self._shed += 1
        return max(1.0, self._inflight / self.max_inflight)

    def _busy(self, tenant: str, pressure: float, reason: str) -> Busy:
        _D_SHED.inc(tenant=tenant)
        telemetry.event("rspc.shed", tenant=tenant, reason=reason)
        return Busy(retry_after_ms=int(self.base_retry_after_ms * pressure),
                    reason=reason)

    def _release(self, tenant: str, ops: int, nbytes: int) -> None:
        # Admission-token callback (the shared token passes its ops=1)
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            t_inflight = max(0, self._per_tenant.get(tenant, 0) - 1)
            if t_inflight == 0:
                self._per_tenant.pop(tenant, None)
            else:
                self._per_tenant[tenant] = t_inflight
            inflight = self._inflight
        _D_INFLIGHT.set(inflight)

    # -- introspection -------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "budget_inflight": self.max_inflight,
                "in_flight": self._inflight,
                "tenants_in_flight": len(self._per_tenant),
                "shed": self._shed,
            }
