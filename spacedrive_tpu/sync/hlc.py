"""Hybrid logical clock — the uhlc-equivalent ordering primitive.

The reference orders CRDT ops by a uhlc NTP64 timestamp (core/crates/sync/src/
manager.rs:44, crdt.rs:25-131). Same shape here: a 64-bit timestamp whose high
32 bits are unix seconds and low 32 bits are fraction, made strictly monotonic
per library by bumping past the last seen value (local or remote). Fits SQLite
INTEGER (i64) until year 2106.
"""

from __future__ import annotations

import threading
import time


def ntp64(unix_seconds: float) -> int:
    sec = int(unix_seconds)
    frac = int((unix_seconds - sec) * (1 << 32))
    return (sec << 32) | (frac & 0xFFFFFFFF)


def to_unix(ts: int) -> float:
    return (ts >> 32) + (ts & 0xFFFFFFFF) / (1 << 32)


class HLC:
    """Monotonic hybrid clock; thread-safe (domain writers + ingest thread)."""

    def __init__(self, last: int = 0) -> None:
        self._last = last
        self._lock = threading.Lock()

    def now(self) -> int:
        with self._lock:
            self._last = max(ntp64(time.time()), self._last + 1)
            return self._last

    def update(self, remote_ts: int) -> None:
        """Witness a remote timestamp (ingest.rs HLC update on receive)."""
        with self._lock:
            self._last = max(self._last, remote_ts)

    @property
    def last(self) -> int:
        with self._lock:
            return self._last
