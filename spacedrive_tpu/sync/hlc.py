"""Hybrid logical clock — the uhlc-equivalent ordering primitive.

The reference orders CRDT ops by a uhlc NTP64 timestamp (core/crates/sync/src/
manager.rs:44, crdt.rs:25-131). Same shape here: a 64-bit timestamp whose high
32 bits are unix seconds and low 32 bits are fraction, made strictly monotonic
per library by bumping past the last seen value (local or remote). Fits SQLite
INTEGER (i64) until year 2106.
"""

from __future__ import annotations

import threading
import time


def ntp64(unix_seconds: float) -> int:
    sec = int(unix_seconds)
    frac = int((unix_seconds - sec) * (1 << 32))
    return (sec << 32) | (frac & 0xFFFFFFFF)


def to_unix(ts: int) -> float:
    return (ts >> 32) + (ts & 0xFFFFFFFF) / (1 << 32)


#: Maximum tolerated clock skew when witnessing a remote timestamp. uhlc
#: rejects timestamps beyond a drift bound for the same reason: one peer
#: sending a timestamp near 2^63 would otherwise permanently poison the
#: library clock (it persists via the op-log floor across restarts) and
#: eventually overflow SQLite's i64 as local ops bump past it. Accepting
#: far-future stamps is also an LWW exploit: a "year 2100" update would win
#: every per-field arbitration forever. Tradeoff: an honest peer skewed
#: more than this replicates with a (skew − bound) delay — its ops sort
#: after all sane ops, so they wait at the window tail (never blocking
#: other instances) and apply once wall time catches up.
MAX_DRIFT_SECONDS = 900


class HLC:
    """Monotonic hybrid clock; thread-safe (domain writers + ingest thread)."""

    def __init__(self, last: int = 0) -> None:
        self._last = last
        self._lock = threading.Lock()

    def now(self) -> int:
        with self._lock:
            self._last = max(ntp64(time.time()), self._last + 1)
            return self._last

    def update(self, remote_ts: int) -> bool:
        """Witness a remote timestamp (ingest.rs HLC update on receive).
        Returns False — without witnessing — for anything that is not a
        plausible NTP64 instant within the drift bound."""
        if not isinstance(remote_ts, int) or isinstance(remote_ts, bool) \
                or remote_ts <= 0:
            return False
        if remote_ts > ntp64(time.time() + MAX_DRIFT_SECONDS):
            return False
        with self._lock:
            self._last = max(self._last, remote_ts)
        return True

    @property
    def last(self) -> int:
        with self._lock:
            return self._last
