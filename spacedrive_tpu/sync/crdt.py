"""CRDT operation types + wire format.

Same algebra as the reference (crates/sync/src/crdt.rs:25-131): per-record
shared ops (Create / per-field Update with last-write-wins / Delete) and
many-many relation ops, each stamped with (instance pub_id, HLC timestamp,
op uuid). The wire format is plain JSON-safe dicts — no codegen; the model
layer's ``SYNC`` annotations (models/schema.py) drive application.

Foreign keys never cross the wire as local integer ids: factories emit
``ref(model, pub_id)`` markers that the applier resolves against the local
database (the reference reaches the same end via per-model SyncId types
emitted by sd-sync-generator, crates/sync-generator/src/lib.rs:22-36).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any

# op kinds as stored in the op-log `kind` column
CREATE = "c"
DELETE = "d"
UPDATE_PREFIX = "u:"  # "u:<field>"


def ref(table: str, pub_id: Any) -> dict[str, Any]:
    """FK value marker: resolved to the local row id at apply time."""
    return {"__ref__": [table, pub_id]}


def is_ref(value: Any) -> bool:
    return isinstance(value, dict) and "__ref__" in value


@dataclasses.dataclass
class SharedOp:
    """Record-level op on a ``SYNC = Shared(id=...)`` model."""

    model: str               # table name
    record_id: Any           # the Shared.id field value (usually pub_id)
    kind: str                # CREATE | DELETE | "u:<field>"
    data: Any                # CREATE: {field: value}; UPDATE: value; DELETE: None


@dataclasses.dataclass
class RelationOp:
    """Link-table op on a ``SYNC = Relation(item, group)`` model."""

    relation: str            # link table name
    item_id: Any             # item-side pub_id
    group_id: Any            # group-side pub_id
    kind: str                # CREATE | DELETE | "u:<field>"
    data: Any


@dataclasses.dataclass
class CRDTOperation:
    instance: str            # origin instance pub_id
    timestamp: int           # HLC NTP64
    id: str                  # op uuid
    typ: SharedOp | RelationOp

    def to_wire(self) -> dict[str, Any]:
        # hand-rolled (not dataclasses.asdict): asdict deep-copies the data
        # payload and dominates the sender side of big pull windows; wire
        # dicts are treated as read-only by every consumer
        t = self.typ
        if isinstance(t, SharedOp):
            body = {"model": t.model, "record_id": t.record_id,
                    "kind": t.kind, "data": t.data, "_t": "shared"}
        else:
            body = {"relation": t.relation, "item_id": t.item_id,
                    "group_id": t.group_id, "kind": t.kind, "data": t.data,
                    "_t": "relation"}
        return {"instance": self.instance, "timestamp": self.timestamp,
                "id": self.id, "typ": body}

    @classmethod
    def from_wire(cls, wire: dict[str, Any]) -> "CRDTOperation":
        """Strict decode: ops arrive from remote peers, so every structural
        assumption the ingest path relies on (string ids, int timestamp,
        known tag, exact field set) is enforced here — a malformed op must
        fail *at decode*, where ingest can skip it, not deep inside a DB
        statement."""
        if not isinstance(wire, dict) or not isinstance(wire.get("typ"), dict):
            raise ValueError("op is not a tagged dict")
        body = dict(wire["typ"])
        tag = body.pop("_t", None)
        typ: SharedOp | RelationOp
        if tag == "shared":
            typ = SharedOp(**body)
            if not isinstance(typ.model, str):
                raise ValueError("shared op model must be a string")
        elif tag == "relation":
            typ = RelationOp(**body)
            if not isinstance(typ.relation, str):
                raise ValueError("relation op relation must be a string")
        else:
            raise ValueError(f"unknown op tag {tag!r}")
        if not isinstance(typ.kind, str) or not (
                typ.kind in (CREATE, DELETE) or typ.kind.startswith(UPDATE_PREFIX)):
            raise ValueError(f"unknown op kind {typ.kind!r}")
        op = cls(instance=wire["instance"], timestamp=wire["timestamp"],
                 id=wire["id"], typ=typ)
        if not isinstance(op.instance, str) or not isinstance(op.id, str) \
                or not isinstance(op.timestamp, int) \
                or isinstance(op.timestamp, bool):
            raise ValueError("op envelope fields have wrong types")
        return op


def new_op(instance: str, timestamp: int, typ: SharedOp | RelationOp) -> CRDTOperation:
    return CRDTOperation(instance=instance, timestamp=timestamp,
                         id=str(uuid.uuid4()), typ=typ)
