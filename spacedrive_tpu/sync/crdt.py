"""CRDT operation types + wire format.

Same algebra as the reference (crates/sync/src/crdt.rs:25-131): per-record
shared ops (Create / per-field Update with last-write-wins / Delete) and
many-many relation ops, each stamped with (instance pub_id, HLC timestamp,
op uuid). The wire format is plain JSON-safe dicts — no codegen; the model
layer's ``SYNC`` annotations (models/schema.py) drive application.

Foreign keys never cross the wire as local integer ids: factories emit
``ref(model, pub_id)`` markers that the applier resolves against the local
database (the reference reaches the same end via per-model SyncId types
emitted by sd-sync-generator, crates/sync-generator/src/lib.rs:22-36).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any

# op kinds as stored in the op-log `kind` column
CREATE = "c"
DELETE = "d"
UPDATE_PREFIX = "u:"  # "u:<field>"


def ref(table: str, pub_id: Any) -> dict[str, Any]:
    """FK value marker: resolved to the local row id at apply time."""
    return {"__ref__": [table, pub_id]}


def is_ref(value: Any) -> bool:
    return isinstance(value, dict) and "__ref__" in value


@dataclasses.dataclass
class SharedOp:
    """Record-level op on a ``SYNC = Shared(id=...)`` model."""

    model: str               # table name
    record_id: Any           # the Shared.id field value (usually pub_id)
    kind: str                # CREATE | DELETE | "u:<field>"
    data: Any                # CREATE: {field: value}; UPDATE: value; DELETE: None


@dataclasses.dataclass
class RelationOp:
    """Link-table op on a ``SYNC = Relation(item, group)`` model."""

    relation: str            # link table name
    item_id: Any             # item-side pub_id
    group_id: Any            # group-side pub_id
    kind: str                # CREATE | DELETE | "u:<field>"
    data: Any


@dataclasses.dataclass
class CRDTOperation:
    instance: str            # origin instance pub_id
    timestamp: int           # HLC NTP64
    id: str                  # op uuid
    typ: SharedOp | RelationOp

    def to_wire(self) -> dict[str, Any]:
        t = self.typ
        body = dataclasses.asdict(t)
        body["_t"] = "shared" if isinstance(t, SharedOp) else "relation"
        return {"instance": self.instance, "timestamp": self.timestamp,
                "id": self.id, "typ": body}

    @classmethod
    def from_wire(cls, wire: dict[str, Any]) -> "CRDTOperation":
        body = dict(wire["typ"])
        kind = body.pop("_t")
        typ: SharedOp | RelationOp
        if kind == "shared":
            typ = SharedOp(**body)
        else:
            typ = RelationOp(**body)
        return cls(instance=wire["instance"], timestamp=wire["timestamp"],
                   id=wire["id"], typ=typ)


def new_op(instance: str, timestamp: int, typ: SharedOp | RelationOp) -> CRDTOperation:
    return CRDTOperation(instance=instance, timestamp=timestamp,
                         id=str(uuid.uuid4()), typ=typ)
