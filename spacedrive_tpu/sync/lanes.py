"""Partitioned sync ingest: shard windows into K independent apply lanes.

Until ISSUE 8 the CRDT ingest path was one serialized lane: every window
from every peer queued behind a single ``Ingester.receive``. This module is
the SEDD shape (PAPERS.md, arxiv 2501.01046 — many independent shards
batched through one accelerator-adjacent node) applied to ingest: incoming
windows are sharded by **(model, record-id prefix)** into K lanes, each a
worker thread with its own bounded queue and its own per-peer
:class:`~.ingest.Ingester` (session-txn batching from PR 3 intact), so
independent records arbitrate and apply concurrently while records that
share arbitration history never split across lanes.

Why this is convergence-safe (the K∈{1,4} byte-identity gate):

- arbitration is strictly **per record** — a shared op's effect depends
  only on its own record's logged history, and one record's ops always
  land in one lane, in window order;
- arrival order across records provably does not matter (the 4!-
  permutation test in tests/test_sync.py) — lanes only reorder across
  records;
- ops whose application READS other records (relation ops linking two
  endpoints, shared ops carrying ``ref`` FK markers) are deferred to a
  **second wave** applied after every lane of the window drains, so a
  referenced row created elsewhere in the same window is present exactly
  as it would be under serial timestamp-ordered apply;
- instance clock floors are merged across lanes after the barrier — a
  poison in one lane caps the floor below itself even when another lane
  applied later ops from the same instance — and persisted only once all
  lane transactions committed (floors never run ahead of durability).

The pool is **per library** (the apply side is single-writer per library
DB; lanes overlap decode, prefetch SELECTs on the reader connection, and
arbitration while durable writes serialize on the writer lock) and shared
by every ingest source: the pull Actor, p2p responder sessions, and the
fleet harness all submit to the same K lanes.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

from .. import telemetry
from ..models import Instance
from ..telemetry import mesh
from ..utils.locks import SdLock
from .crdt import is_ref
from .ingest import _WINDOW_SECONDS, Ingester, shared_poison_caps

if TYPE_CHECKING:
    from ..library import Library

logger = logging.getLogger(__name__)

#: K — 1 keeps the exact pre-lane single-path behavior (the default)
LANES_ENV = "SD_SYNC_INGEST_LANES"
#: bounded depth of each lane's work queue (submissions, not ops); a full
#: lane applies backpressure to the submitter, never unbounded buffering
DEPTH_ENV = "SD_SYNC_LANE_DEPTH"
MAX_LANES = 16

_LANE_COUNT = telemetry.gauge(
    "sd_sync_ingest_lane_count", "configured sync ingest apply lanes")
_LANE_DEPTH = telemetry.gauge(
    "sd_sync_ingest_lane_depth", "queued submissions per ingest lane",
    labels=("lane",))
_LANE_BUSY = telemetry.gauge(
    "sd_sync_ingest_lane_busy", "1 while the lane is applying a shard",
    labels=("lane",))
_LANE_OPS = telemetry.counter(
    "sd_sync_ingest_lane_ops_total", "CRDT ops applied per ingest lane",
    labels=("lane",))


def lane_count() -> int:
    try:
        n = int(os.environ.get(LANES_ENV, "1"))
    except ValueError:
        return 1
    return max(1, min(MAX_LANES, n))


def _lane_depth() -> int:
    try:
        n = int(os.environ.get(DEPTH_ENV, "8"))
    except ValueError:
        return 8
    return max(1, n)


def _has_ref(data: Any) -> bool:
    if is_ref(data):
        return True
    if isinstance(data, dict):
        return any(is_ref(v) for v in data.values())
    return False


def lane_key(wire: dict[str, Any], lanes: int) -> int | None:
    """Shard index for one wire op, or ``None`` for the deferred second
    wave (ops whose APPLICATION reads other records: relation links and
    ``ref``-carrying shared ops). Sharding is (model, record-id prefix) —
    deterministic, so a poisoned record replays into the same lane."""
    typ = wire.get("typ")
    if not isinstance(typ, dict):
        return 0  # malformed: any lane may drop it
    if typ.get("_t") == "relation":
        return None
    if _has_ref(typ.get("data")):
        return None
    key = f"{typ.get('model')}\x00{str(typ.get('record_id'))[:8]}"
    return zlib.crc32(key.encode("utf-8", "replace")) % lanes


@dataclass
class _LaneTask:
    """One lane's share of a submission: the per-window shards, in window
    order, applied under one session transaction."""

    ingester: Ingester
    parts: list[tuple[list[dict[str, Any]], Any]]
    done: threading.Event = field(default_factory=threading.Event)
    applied: int = 0
    clocks: dict[str, int] = field(default_factory=dict)
    caps: dict[str, int] = field(default_factory=dict)
    error: BaseException | None = None


class Submission:
    """Handle for one in-flight lane submission (ROADMAP fleet rung (b)).

    ``submit()`` returns immediately after the lane shards are enqueued,
    so lane K of window N overlaps window N+1's decode/enqueue — the
    merger thread completes submissions strictly in submission order
    (wave-2 apply, then the cross-lane floor merge), preserving the
    ordering rule floors depend on. ``wait()`` blocks until this
    submission's floors persisted (or raises its first error) — exactly
    the old barrier semantics, now opt-out per submission."""

    __slots__ = ("windows", "peer", "label", "tasks", "wave2", "t0",
                 "applied", "advanced", "error", "_done", "_fanout_done")

    def __init__(self, windows, peer: str | None, label: str) -> None:
        self.windows = windows
        self.peer = peer
        self.label = label
        self.tasks: list[tuple[int, _LaneTask]] = []
        self.wave2: list[tuple[list[dict[str, Any]], Any]] = []
        self.t0 = time.perf_counter()
        self.applied = 0
        self.advanced = False
        self.error: BaseException | None = None
        self._done = threading.Event()
        self._fanout_done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> tuple[int, bool]:
        if not self._done.wait(timeout):
            raise TimeoutError("lane submission still in flight")
        if self.error is not None:
            raise self.error
        return self.applied, self.advanced

    def _finish(self, error: BaseException | None = None) -> None:
        # first finisher wins: the close()/submit ticket race can have
        # both the merger and the submitter trying to settle one handle
        if self._done.is_set():
            return
        self.error = error
        self._done.set()


class IngestLanes:
    """K apply lanes over one library. ``receive``/``receive_many`` block
    until the submission is durable and the merged clock floors are
    persisted; ``submit`` returns a :class:`Submission` handle instead, so
    a pipelining submitter (the fleet harness's WAN sessions) can overlap
    window N+1's decode with window N's apply. Submissions COMPLETE in
    submission order regardless (the merger thread), so the cross-lane
    floor-merge ordering rule — floors persist only after every lane txn
    of that submission committed, and never out of order — holds under
    pipelining exactly as under the barrier."""

    def __init__(self, library: "Library", lanes: int | None = None,
                 depth: int | None = None) -> None:
        self.library = library
        self.lanes = lanes if lanes is not None else lane_count()
        self._depth = depth if depth is not None else _lane_depth()
        self._lock = SdLock("sync.lanes.state")
        #: (peer, lane index) -> Ingester — an ingester's batch caches and
        #: poison memory are single-threaded state, so each is owned by
        #: exactly one lane thread (plus one wave-2 ingester per peer,
        #: used only on the merger thread under _wave2_lock)
        self._ingesters: dict[tuple[str | None, int], Ingester] = {}
        self._queues: list[queue.Queue[_LaneTask | None]] = []
        self._threads: list[threading.Thread] = []
        self._wave2_lock = SdLock("sync.lanes.wave2")
        self._closed = False
        self._windows = 0
        self._submissions = 0
        self._merge_q: queue.Queue[Submission | None] = queue.Queue(
            maxsize=max(2, self._depth))
        self._merger: threading.Thread | None = None
        if self.lanes > 1:
            for i in range(self.lanes):
                q: queue.Queue[_LaneTask | None] = queue.Queue(
                    maxsize=self._depth)
                t = threading.Thread(
                    target=self._worker, args=(i, q), daemon=True,
                    name=f"sync-lane-{library.id[:8]}-{i}")
                self._queues.append(q)
                self._threads.append(t)
                t.start()
            self._merger = threading.Thread(
                target=self._merge_loop, daemon=True,
                name=f"sync-merge-{library.id[:8]}")
            self._merger.start()
        _LANE_COUNT.set(self.lanes)

    # -- public entry points -------------------------------------------------
    def receive(self, ops: list[dict[str, Any]], ctx=None,
                peer: str | None = None) -> tuple[int, bool]:
        """One window. Returns (applied, floor_advanced)."""
        return self.receive_many([(ops, ctx)], peer=peer)

    def receive_many(self, windows: list[tuple[list[dict[str, Any]], Any]],
                     peer: str | None = None) -> tuple[int, bool]:
        """Apply several buffered windows (the Actor's flush group) as one
        submission and BLOCK until its floors persisted — the pre-pipeline
        barrier semantics, kept for the p2p responder and the Actor."""
        if not windows:
            return 0, False
        if self.lanes <= 1:
            return self._receive_serial(windows, peer)
        return self.submit(windows, peer=peer).wait()

    def submit(self, windows: list[tuple[list[dict[str, Any]], Any]],
               peer: str | None = None) -> Submission:
        """Enqueue one submission's lane shards and return its handle
        WITHOUT waiting for the apply: lane K starts on window N while the
        submitter decodes/admits window N+1 (ROADMAP fleet rung (b)).
        Backpressure is intact — bounded lane queues block this call, and
        the bounded merge queue caps how many submissions can be in flight
        at once. Window order is preserved within every lane (per-lane
        FIFO) and across submissions (one merger, submission order)."""
        if self.lanes <= 1:
            # serial path has no lanes to overlap: complete synchronously
            sub = Submission(windows, peer, mesh.peer_label(peer))
            try:
                sub.applied, sub.advanced = self._receive_serial(
                    windows, peer)
                sub._finish()
            except BaseException as e:  # noqa: BLE001 — surfaced by wait()
                sub._finish(e)
            return sub
        sub = Submission(windows, peer, mesh.peer_label(peer))
        with self._lock:  # concurrent submitters: += is read-then-write
            self._submissions += 1
        # shard every window; wave-2 ops keep original (window, op) order
        lane_parts: list[list[tuple[list[dict[str, Any]], Any]]] = [
            [] for _ in range(self.lanes)]
        for ops, ctx in windows:
            shards: list[list[dict[str, Any]]] = [
                [] for _ in range(self.lanes)]
            deferred: list[dict[str, Any]] = []
            for wire in ops:
                idx = lane_key(wire, self.lanes)
                if idx is None:
                    deferred.append(wire)
                else:
                    shards[idx].append(wire)
            for i, shard in enumerate(shards):
                if shard:
                    lane_parts[i].append((shard, ctx))
            if deferred:
                sub.wave2.append((deferred, ctx))

        # enqueue the merge ticket FIRST: the merger completes submissions
        # strictly in ticket order, so a later submit can never merge its
        # floors ahead of this one (bounded: caps in-flight submissions)
        while True:
            if self._closed:
                raise RuntimeError("ingest lane pool is closed")
            try:
                self._merge_q.put(sub, timeout=1.0)
                break
            except queue.Full:
                continue
        if self._closed:
            # close() may have sentineled + drained between our closed
            # check and the put — the ticket would sit unserviced forever;
            # settle the handle ourselves (first-finisher-wins: a merger
            # that DID race us to it already settled it, we no-op).
            # Finish BEFORE releasing the fan-out event: a merger still
            # draining tickets must see done() and skip, never complete a
            # submission whose shards were never enqueued.
            err = RuntimeError("ingest lane pool is closed")
            sub._finish(err)
            sub._fanout_done.set()
            raise err
        # fan the shards out (bounded queues: a saturated lane blocks the
        # submitter — backpressure, not buffering). A failure mid-fanout
        # FAILS the whole submission before releasing the merger: merging
        # the enqueued subset's floors could advance past the ops of a
        # shard that never made it to its lane.
        try:
            for i, parts in enumerate(lane_parts):
                if not parts:
                    continue
                task = _LaneTask(self._ingester(peer, i), parts)
                while True:
                    if self._closed:
                        raise RuntimeError("ingest lane pool is closed")
                    try:
                        self._queues[i].put(task, timeout=1.0)
                        break
                    except queue.Full:
                        continue
                _LANE_DEPTH.set(self._queues[i].qsize(), lane=str(i))
                sub.tasks.append((i, task))
        except BaseException as e:
            # lanes that DID get their shards may log ops while the floor
            # merge is skipped; protect this submission's ops from being
            # floor-leapfrogged by other in-flight submissions
            self._protect_unpersisted(sub)
            sub._finish(e)            # before the fan-out event: the
            sub._fanout_done.set()    # merger's done() check sees it
            raise
        # the merger may already be waiting on this ticket; mark the shard
        # fan-out complete so it knows the task list is final
        sub._fanout_done.set()
        return sub

    def _protect_unpersisted(self, sub: Submission) -> None:
        """A FAILED submission persists no floors, but some of its ops may
        sit durably logged in lanes that committed — and submissions still
        in flight behind it (a pipelining session, another peer forwarding
        the same origin instances) may carry HIGHER timestamps whose floor
        merge would silently leapfrog the failed submission's never-logged
        ops (lost forever: the retry pulls from durable floors). Register
        every op of the failed submission in the library-wide sticky caps:
        every later floor merge stays capped below them until each op is
        durably logged on re-delivery (the heal paths in
        Ingester._ingest_pass), exactly the poison-op discipline."""
        caps = shared_poison_caps(self.library)
        for ops, _ctx in sub.windows:
            for wire in ops:
                op_id = wire.get("id")
                if isinstance(op_id, str):
                    caps.add(op_id, wire.get("instance"),
                             wire.get("timestamp"))

    # -- the ordered merger ---------------------------------------------------
    def _merge_loop(self) -> None:
        while True:
            sub = self._merge_q.get()
            if sub is None:
                return
            try:
                self._complete(sub)
            except BaseException as e:  # noqa: BLE001 — handed to wait()
                sub._finish(e)

    def _complete(self, sub: Submission) -> None:
        """Barrier on one submission's lane tasks, run its wave 2, merge +
        persist floors, record mesh windows — in merger-thread order."""
        # the submitter enqueues the merge ticket before the lane shards;
        # wait for the fan-out to finish so sub.tasks is complete
        while not sub._fanout_done.wait(timeout=0.2):
            if self._closed:
                self._protect_unpersisted(sub)
                sub._finish(RuntimeError("ingest lane pool closed with a "
                                         "submission in flight"))
                return
        if sub.done():
            return  # the submitter failed the fan-out; persist nothing
        for _i, task in sub.tasks:
            while not task.done.wait(timeout=1.0):
                # close() fails drained tasks; a task that raced in after
                # the drain would otherwise strand the merger forever
                if self._closed and not task.done.wait(timeout=2.0):
                    self._protect_unpersisted(sub)
                    sub._finish(RuntimeError(
                        "ingest lane pool closed with a submission "
                        "in flight"))
                    return

        applied = sum(t.applied for _i, t in sub.tasks)
        merged_clocks: dict[str, int] = {}
        merged_caps: dict[str, int] = {}
        first_error: BaseException | None = None
        for _i, task in sub.tasks:
            if task.error is not None:
                first_error = first_error or task.error
                continue
            for pub_id, ts in task.clocks.items():
                if ts > merged_clocks.get(pub_id, 0):
                    merged_clocks[pub_id] = ts
            for pub_id, cap in task.caps.items():
                merged_caps[pub_id] = min(merged_caps.get(pub_id, cap), cap)

        # wave 2: ops that read other records apply AFTER the barrier, in
        # original order, on the merger thread (one merger per pool, so
        # two submissions' wave-2 shards can never interleave an ingester)
        if sub.wave2 and first_error is None:
            w2 = self._ingester(sub.peer, -1)
            try:
                with self._wave2_lock, w2.session():
                    for ops, ctx in sub.wave2:
                        applied += w2.receive(ops, ctx, defer_clocks=True)
                clocks, caps = self._take_deferred(w2)
                for pub_id, ts in clocks.items():
                    if ts > merged_clocks.get(pub_id, 0):
                        merged_clocks[pub_id] = ts
                for pub_id, cap in caps.items():
                    merged_caps[pub_id] = min(
                        merged_caps.get(pub_id, cap), cap)
            except Exception as e:  # lane-equivalent failure: floors hold
                # the rolled-back session's deferred clocks must not
                # linger on the shared wave-2 ingester — a later
                # submission would merge them and advance floors past
                # ops that were never durably logged
                self._take_deferred(w2)
                first_error = e

        # cross-lane floor merge: only-raise, then poison caps only-lower.
        # If ANY lane failed, persist NOTHING: the failed lane may hold
        # earlier ops from the same origin instance as a lane that
        # committed, and advancing the floor past them would lose them
        # forever (the committed lanes' ops are durably LOGGED, so the
        # idempotent re-pull skips them as duplicates — floors catch up
        # on the retry). Under pipelining that is not enough: LATER
        # submissions already in flight may carry higher timestamps of
        # the same instances, and THEIR floor merges would leapfrog this
        # submission's never-logged ops — sticky-cap them first.
        if first_error is not None:
            self._protect_unpersisted(sub)
            sub._finish(first_error)
            return
        # clamp with the LIVE library-wide sticky caps too: a lane task of
        # this submission may have computed its end-of-pass caps BEFORE an
        # earlier submission's merger-time failure registered protection
        # for the same instances (the tasks run concurrently; only the
        # merger is ordered) — re-reading here, in merger order, closes
        # that window
        for pub_id, cap in shared_poison_caps(self.library) \
                .floor_caps().items():
            merged_caps[pub_id] = min(merged_caps.get(pub_id, cap), cap)
        for pub_id, cap in merged_caps.items():
            if merged_clocks.get(pub_id, 0) > cap:
                merged_clocks[pub_id] = cap
        advanced = self._persist_floors(merged_clocks)

        # window-level mesh recording (the lanes skipped it): lag gauges
        # from the LAST window's envelope, window count per window. No
        # window is durable before the barrier + floor merge, so the
        # submission's wall time is split across its windows — count
        # matches the serial path's one-observe-per-window and the _sum
        # stays the real wall time, not windows× it.
        elapsed = time.perf_counter() - sub.t0
        window_seconds = _WINDOW_SECONDS.labels(peer=sub.label)
        per_window_s = elapsed / len(sub.windows)
        for ops, ctx in sub.windows:
            max_ts = max((w.get("timestamp") for w in ops
                          if isinstance(w.get("timestamp"), int)),
                         default=0)
            mesh.record_ingest_window(sub.label, ctx, max_ts)
            window_seconds.observe(per_window_s)
            with self._lock:  # merger thread races K=1 submitters
                self._windows += 1
        logger.debug("lane ingest: %d windows, %d applied in %.3fs",
                     len(sub.windows), applied, elapsed)
        sub.applied = applied
        sub.advanced = advanced
        sub._finish()

    def _receive_serial(self, windows, peer: str | None) -> tuple[int, bool]:
        """K=1: the exact pre-lane path (session-grouped windows)."""
        ing = self._ingester(peer, 0)
        applied = 0
        with ing.session():
            for ops, ctx in windows:
                applied += ing.receive(ops, ctx)
        with self._lock:  # K=1 serial windows arrive from many threads
            self._windows += len(windows)
            self._submissions += 1
        return applied, ing.last_floor_advanced

    # -- internals -----------------------------------------------------------
    def _ingester(self, peer: str | None, lane: int) -> Ingester:
        with self._lock:
            ing = self._ingesters.get((peer, lane))
            if ing is None:
                ing = Ingester(self.library, peer=peer)
                self._ingesters[(peer, lane)] = ing
            return ing

    @staticmethod
    def _take_deferred(ing: Ingester) -> tuple[dict[str, int], dict[str, int]]:
        clocks, caps = ing.deferred_clocks, ing.deferred_caps
        ing.deferred_clocks, ing.deferred_caps = {}, {}
        return clocks, caps

    def _persist_floors(self, clocks: dict[str, int]) -> bool:
        """Only-raise floor persistence, AFTER every lane txn committed —
        a floor must never run ahead of the durability of its ops."""
        if not clocks:
            return False
        db = self.library.db
        advanced = False
        with db.transaction():
            for pub_id, ts in clocks.items():
                row = db.find_one(Instance, {"pub_id": pub_id})
                if row is not None and (row["timestamp"] or 0) < ts:
                    db.update(Instance, {"pub_id": pub_id},
                              {"timestamp": ts})
                    advanced = True
        return advanced

    def _worker(self, idx: int, q: "queue.Queue[_LaneTask | None]") -> None:
        lane = str(idx)
        busy = _LANE_BUSY.labels(lane=lane)
        depth = _LANE_DEPTH.labels(lane=lane)
        ops_total = _LANE_OPS.labels(lane=lane)
        while True:
            task = q.get()
            depth.set(q.qsize())
            if task is None:
                return
            busy.set(1)
            try:
                ing = task.ingester
                with ing.session():  # one durable txn per lane task
                    for ops, ctx in task.parts:
                        task.applied += ing.receive(ops, ctx,
                                                    defer_clocks=True)
                task.clocks, task.caps = self._take_deferred(ing)
                ops_total.inc(sum(len(ops) for ops, _ in task.parts))
            except Exception as e:
                # session txn rolled back: none of this lane's shards are
                # durable, so its clocks must not merge (re-pulled intact)
                self._take_deferred(task.ingester)
                task.error = e
                logger.exception("ingest lane %d failed", idx)
            finally:
                busy.set(0)
                task.done.set()

    # -- lifecycle / introspection -------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=5)
        # fail any task queued behind the sentinel so its submitter
        # unblocks with an error instead of waiting on a dead worker
        for q in self._queues:
            while True:
                try:
                    task = q.get_nowait()
                except queue.Empty:
                    break
                if task is not None and not task.done.is_set():
                    task.error = RuntimeError("ingest lane pool closed")
                    task.done.set()
        # stop the merger and fail any submission still ticketed so a
        # pipelining submitter's wait() unblocks with an error
        if self._merger is not None:
            self._merge_q.put(None)
            self._merger.join(timeout=5)
            while True:
                try:
                    sub = self._merge_q.get_nowait()
                except queue.Empty:
                    break
                if sub is not None and not sub.done():
                    self._protect_unpersisted(sub)
                    sub._finish(RuntimeError("ingest lane pool closed"))

    def status(self) -> dict[str, Any]:
        return {
            "lanes": self.lanes,
            "queue_depths": [q.qsize() for q in self._queues],
            "queue_bound": self._depth,
            "windows": self._windows,
            "submissions": self._submissions,
        }


_POOL_LOCK = SdLock("sync.lanes.pool")


def get_lane_pool(library: "Library", lanes: int | None = None) -> IngestLanes:
    """The library's shared lane pool (memoized on the library object;
    closed with it). Serialized: two first callers racing the check-then-
    set would each build a pool and leak the loser's K lane threads."""
    with _POOL_LOCK:
        pool = library.__dict__.get("_ingest_lanes")
        if pool is None or (lanes is not None and pool.lanes != lanes):
            if pool is not None:
                pool.close()
            pool = IngestLanes(library, lanes=lanes)
            library.__dict__["_ingest_lanes"] = pool
        return pool
