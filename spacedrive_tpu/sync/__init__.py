"""CRDT sync layer (SURVEY.md §2.6).

HLC-ordered per-field last-write-wins replication of library state, matching
the reference's sd-sync design: op factories + atomic op-log emission
(manager.py), annotation-driven application (apply.py), and the pull-based
ingest actor with stale-op rejection (ingest.py). Networking attaches at the
Transport seam — the two-instance integration test (tests/test_sync.py) wires
it to direct calls exactly like the reference's fake-transport test
(core/crates/sync/tests/lib.rs:102-217).
"""

from .admission import Busy, IngestBudget
from .crdt import CREATE, DELETE, UPDATE_PREFIX, CRDTOperation, RelationOp, SharedOp, ref
from .hlc import HLC, ntp64
from .ingest import Actor, Ingester
from .lanes import IngestLanes, get_lane_pool, lane_count
from .manager import SyncManager, SyncMessage

__all__ = [
    "CREATE", "DELETE", "UPDATE_PREFIX", "CRDTOperation", "RelationOp",
    "SharedOp", "ref", "HLC", "ntp64", "Actor", "Busy", "Ingester",
    "IngestBudget", "IngestLanes", "SyncManager", "SyncMessage",
    "get_lane_pool", "lane_count",
]
