"""Structured spans: per-job trace trees with JSONL export.

A :class:`Trace` is one job run's tree of timed spans. The worker opens
the trace (root span = the job), the pipeline executor and job code open
child spans with ``with trace.span("pipeline.page"): ...`` — nesting
follows each *thread's* own span stack (the prefetch/dispatch/commit
threads each build their own chain under the root), so a pipelined run
produces the same tree shape a sequential run does, just with
overlapping timestamps.

Spans always MEASURE (two ``perf_counter`` calls) even when telemetry is
disabled or no trace exists — the stage timings that feed job reports
(``pipeline_page_s``, ``gather_s``…) read span durations, so the report
contract cannot depend on the telemetry switch. Only *recording* (the
tree, the JSONL file, the ``telemetry.jobTrace`` query) is gated: with
no trace a span is a plain timer.

Export: ``<data_dir>/logs/traces/<trace_id>.jsonl``, one span record per
line (trace_id, span_id, parent_id, name, start_unix, duration_s,
attrs). Completed traces also stay in a bounded in-process ring so
``telemetry.jobTrace`` serves them without touching disk.
"""

from __future__ import annotations

import itertools
import json
import logging
import re
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any

logger = logging.getLogger(__name__)

#: completed traces kept in memory for the jobTrace query (ring, FIFO)
MAX_TRACES = 128

ROOT_SPAN_ID = 0

#: the innermost trace the CURRENT THREAD has an open span in — how code
#: far from the worker (the remote-hasher dispatch, a p2p request) finds
#: the trace context to propagate without threading it through every call
_CURRENT = threading.local()

#: the innermost OPEN span per thread id, as (trace_id, span name) — the
#: cross-thread mirror of the thread-local above. The sampling profiler
#: (telemetry/profiler.py) attributes wall samples from ITS thread to the
#: sampled thread's active span, and a thread-local cannot be read from
#: another thread. Plain dict ops are atomic under the GIL; entries are
#: removed when a thread's span stack empties, so the dict stays bounded
#: by live threads.
_ACTIVE_BY_THREAD: dict[int, tuple[str, str]] = {}


def current_trace() -> "Trace | None":
    return getattr(_CURRENT, "trace", None)


def active_span(tid: int) -> tuple[str, str] | None:
    """(trace_id, span name) of the innermost open span on thread ``tid``,
    or None while that thread has no span open — the profiler's
    attribution read (any thread may call this about any other)."""
    return _ACTIVE_BY_THREAD.get(tid)


class Span:
    """A timed section. Context manager; reentrant-unsafe by design (one
    span object = one enter/exit)."""

    __slots__ = ("name", "attrs", "trace", "span_id", "parent_id",
                 "start_unix", "duration_s", "error", "_t0", "_pinned",
                 "_detached")

    def __init__(self, name: str, trace: "Trace | None" = None,
                 attrs: dict[str, Any] | None = None,
                 parent: "Span | None" = None,
                 parent_id: int | None = None,
                 detached: bool = False) -> None:
        self.name = name
        self.trace = trace
        self.attrs = attrs or {}
        self.span_id = -1
        self.parent_id = ROOT_SPAN_ID
        self.start_unix = 0.0
        self.duration_s = 0.0
        self.error = False
        self._t0 = 0.0
        # explicit cross-thread parent (pipeline stage threads open their
        # spans under the job thread's pipeline.run span; the per-thread
        # stack cannot see it). ``parent_id`` pins a parent known only by
        # id — the CROSS-NODE case, where the parent span lives in another
        # process and arrived as a trace-context envelope (telemetry/mesh).
        # a DETACHED span never joins any thread's nesting stack: it is
        # entered on one thread and exited on another (the sharded
        # prefetch page span — opened by the split coordinator, closed by
        # the ordered merger), so stack-based nesting would corrupt the
        # opener's chain. Children attach via an explicit ``parent=`` pin;
        # the detached span itself must pin its own parent (or root).
        self._detached = detached
        self._pinned = False
        if parent is not None and parent.span_id >= 0:
            self.parent_id = parent.span_id
            self._pinned = True
        elif parent_id is not None and parent_id >= 0:
            self.parent_id = parent_id
            self._pinned = True

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (byte counts, batch
        sizes)."""
        self.attrs.update(attrs)

    def elapsed_s(self) -> float:
        """Seconds since entry — usable while the span is still open."""
        return time.perf_counter() - self._t0

    def __enter__(self) -> "Span":
        if self.trace is not None:
            self.trace._enter(self)
        self.start_unix = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, _exc, _tb) -> bool:
        self.duration_s = time.perf_counter() - self._t0
        if exc_type is not None:
            self.error = True
        if self.trace is not None:
            self.trace._exit(self)
        return False


class Trace:
    """One job run's span tree. Thread-safe: each thread nests along its
    own stack; finished spans append under one lock."""

    def __init__(self, trace_id: str, name: str,
                 attrs: dict[str, Any] | None = None,
                 span_id_base: int = 0) -> None:
        self.trace_id = trace_id
        self.name = name
        self.attrs = dict(attrs or {})
        self.finished = False
        self._final_s: float | None = None
        self._lock = threading.Lock()
        # ``span_id_base``: mesh traces allocate ids above a per-node base
        # so two nodes appending to ONE logical trace (stitched later by
        # trace_id) can never collide on span ids
        self._ids = itertools.count(span_id_base + 1)
        self._records: list[dict[str, Any]] = []
        self._tls = threading.local()
        self._root_start_unix = time.time()
        self._root_t0 = time.perf_counter()

    # -- span plumbing -------------------------------------------------------
    def span(self, name: str, parent: Span | None = None,
             parent_id: int | None = None, detached: bool = False,
             **attrs: Any) -> Span:
        """``parent`` pins an explicit (possibly cross-thread) parent;
        ``parent_id`` pins a remote (cross-node) parent by bare id;
        ``detached`` makes a span owned by no thread stack (enter and
        exit may happen on different threads); otherwise the opening
        thread's current span is the parent."""
        return Span(name, trace=self, attrs=attrs, parent=parent,
                    parent_id=parent_id, detached=detached)

    def _stack(self) -> list[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _enter(self, span: Span) -> None:
        if span._detached:
            # no stack, no thread-local bookkeeping: just an id. The
            # parent must be pinned explicitly (or defaults to the root).
            span.span_id = next(self._ids)
            return
        stack = self._stack()
        if not span._pinned:
            span.parent_id = stack[-1].span_id if stack else ROOT_SPAN_ID
        span.span_id = next(self._ids)
        stack.append(span)
        _CURRENT.trace = self
        # deliberately lock-free: each thread writes only ITS OWN key and
        # single dict ops are GIL-atomic; the profiler's cross-thread read
        # tolerates a stale entry (one mis-attributed sample)
        _ACTIVE_BY_THREAD[threading.get_ident()] = (self.trace_id, span.name)  # lint: ok(lock-discipline)

    def current_span_id(self) -> int:
        """Id of the calling thread's innermost open span (the root when
        none is open) — what an outbound trace-context envelope carries."""
        stack = self._stack()
        return stack[-1].span_id if stack else ROOT_SPAN_ID

    def _exit(self, span: Span) -> None:
        if span._detached:
            self._record(span)
            return
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # mismatched nesting: drop back to it
            del stack[stack.index(span):]
        tid = threading.get_ident()
        # lock-free per-thread key writes, like _enter (GIL-atomic)
        if stack:
            _ACTIVE_BY_THREAD[tid] = (self.trace_id, stack[-1].name)  # lint: ok(lock-discipline)
        else:
            _ACTIVE_BY_THREAD.pop(tid, None)  # lint: ok(lock-discipline)
        if not stack and getattr(_CURRENT, "trace", None) is self:
            _CURRENT.trace = None
        self._record(span)

    def _record(self, span: Span) -> None:
        record = {
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "start_unix": round(span.start_unix, 6),
            "duration_s": round(span.duration_s, 6),
        }
        if span.attrs:
            record["attrs"] = span.attrs
        if span.error:
            record["error"] = True
        with self._lock:
            self._records.append(record)

    def event(self, name: str, **attrs: Any) -> None:
        """A zero-duration marker in the tree (fault fired, verdict
        flipped, relay recovered)."""
        record: dict[str, Any] = {
            "span_id": next(self._ids),
            "parent_id": ROOT_SPAN_ID,
            "name": name,
            "start_unix": round(time.time(), 6),
            "duration_s": 0.0,
            "event": True,
        }
        if attrs:
            record["attrs"] = attrs
        with self._lock:
            self._records.append(record)

    # -- lifecycle -----------------------------------------------------------
    def elapsed_s(self) -> float:
        return time.perf_counter() - self._root_t0

    def finish(self) -> None:
        if self.finished:
            return
        final_s = round(self.elapsed_s(), 6)
        root = {
            "span_id": ROOT_SPAN_ID,
            "parent_id": None,
            "name": self.name,
            "start_unix": round(self._root_start_unix, 6),
            "duration_s": final_s,
        }
        if self.attrs:
            root["attrs"] = self.attrs
        with self._lock:
            self._records.append(root)
            self.finished = True
            self._final_s = final_s

    # -- reads ---------------------------------------------------------------
    def records(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def tree(self) -> dict[str, Any]:
        recs = self.records()
        if not any(r["span_id"] == ROOT_SPAN_ID for r in recs):
            recs.append({"span_id": ROOT_SPAN_ID, "parent_id": None,
                         "name": self.name,
                         "start_unix": round(self._root_start_unix, 6),
                         "duration_s": round(self.elapsed_s(), 6),
                         "attrs": self.attrs or {}})
        return build_tree(self.trace_id, recs)

    def totals(self) -> dict[str, dict[str, float]]:
        """Aggregate finished spans by name: {name: {count, total_s}} —
        the summarized form attached to JobReport metadata."""
        out: dict[str, dict[str, float]] = {}
        for r in self.records():
            if r.get("event"):
                continue
            agg = out.setdefault(r["name"], {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] = round(agg["total_s"] + r["duration_s"], 6)
        return out

    def total_s(self, name: str) -> float:
        """Summed duration of every finished span called ``name`` — how
        stage timings flow from span data back into job metadata."""
        return self.totals().get(name, {}).get("total_s", 0.0)

    def summary(self) -> dict[str, Any]:
        # a finished trace's duration is FROZEN at finish() — snapshots
        # read long after completion must not report ever-growing values
        final = self._final_s
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "duration_s": (final if final is not None
                           else round(self.elapsed_s(), 6)),
            "spans": self.totals(),
        }


def build_tree(trace_id: str, records: list[dict[str, Any]]) -> dict[str, Any]:
    """Nest flat span records into the root's tree (children ordered by
    start time). Orphans (parent never finished) attach to the root."""
    nodes = {r["span_id"]: {**r, "children": []} for r in records}
    root = nodes.get(ROOT_SPAN_ID)
    if root is None:
        root = {"span_id": ROOT_SPAN_ID, "parent_id": None, "name": "?",
                "start_unix": 0.0, "duration_s": 0.0, "children": []}
        nodes[ROOT_SPAN_ID] = root
    for r in sorted(records, key=lambda r: (r["start_unix"], r["span_id"])):
        if r["span_id"] == ROOT_SPAN_ID:
            continue
        parent = nodes.get(r.get("parent_id"), root)
        if parent is nodes[r["span_id"]]:
            parent = root
        parent["children"].append(nodes[r["span_id"]])
    root["trace_id"] = trace_id
    return root


# -- the in-process trace ring -------------------------------------------------

_TRACES_LOCK = threading.Lock()
_TRACES: "OrderedDict[str, Trace]" = OrderedDict()


def remember(trace: Trace) -> None:
    with _TRACES_LOCK:
        _TRACES[trace.trace_id] = trace
        _TRACES.move_to_end(trace.trace_id)
        while len(_TRACES) > MAX_TRACES:
            _TRACES.popitem(last=False)


def get_trace(trace_id: str) -> Trace | None:
    with _TRACES_LOCK:
        return _TRACES.get(trace_id)


def recent_traces(limit: int = 16) -> list[dict[str, Any]]:
    with _TRACES_LOCK:
        traces = list(_TRACES.values())[-limit:]
    return [t.summary() for t in reversed(traces)]


def clear_traces() -> None:
    with _TRACES_LOCK:
        _TRACES.clear()


# -- JSONL export / reload -----------------------------------------------------

def traces_dir(base_dir: str | Path) -> Path:
    return Path(base_dir) / "logs" / "traces"


def export_trace(trace: Trace, base_dir: str | Path) -> str | None:
    """Write one JSONL file per trace — atomically (tempfile→fsync→rename,
    utils/atomic), so a kill mid-export can never leave a torn file under
    the export name. Best-effort: a full disk (the ``trace_export`` chaos
    seam rehearses it) degrades to the in-memory ring only, and must not
    fail the job that owns the trace."""
    try:
        from ..utils.atomic import atomic_write_text

        from .. import faults

        faults.inject("trace_export", key=trace.trace_id)
        out_dir = traces_dir(base_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / f"{trace.trace_id}.jsonl"
        atomic_write_text(path, "".join(
            json.dumps({"trace_id": trace.trace_id, **record}, default=str)
            + "\n" for record in trace.records()))
        return str(path)
    except OSError as e:
        import errno as _errno

        if getattr(e, "errno", None) == _errno.ENOSPC:
            from ..recovery import note_disk_full

            note_disk_full("trace_export")
        logger.exception("could not export trace %s (serving from the "
                         "in-memory ring only)", trace.trace_id)
        return None


#: trace ids are job-report UUIDs; anything else (path separators, "..")
#: must never reach the filesystem — jobTrace takes caller-supplied ids
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def load_trace_tree(trace_id: str, base_dir: str | Path) -> dict[str, Any] | None:
    """Rebuild an exported trace's tree (the jobTrace fallback after the
    in-memory ring evicted it or the process restarted).

    Tolerates torn lines: a crash mid-append (pre-atomic exports, or a
    file truncated by a full disk) leaves a final line cut mid-record —
    that line is skipped with a warning instead of poisoning the whole
    export. Only a file with NO decodable record reads as missing."""
    if not _TRACE_ID_RE.match(trace_id) or ".." in trace_id:
        return None
    path = traces_dir(base_dir) / f"{trace_id}.jsonl"
    try:
        lines = path.read_text().splitlines()
    except OSError:
        return None
    records = []
    dropped = 0
    for line in lines:
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            dropped += 1
            continue
        if isinstance(record, dict) and "span_id" in record:
            records.append(record)
        else:
            dropped += 1
    if dropped:
        logger.warning("trace %s: skipped %d torn/garbage line(s) in %s",
                       trace_id, dropped, path.name)
    if not records:
        return None
    return build_tree(trace_id, records)
