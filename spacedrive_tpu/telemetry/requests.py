"""Per-procedure request telemetry for the serving tier (ISSUE 10).

Every rspc dispatch (``api/router.py resolve()``) runs through
:func:`observed`, which maintains the ``sd_rspc_*`` families — request
counts by ``{proc, kind, outcome}``, a per-procedure latency histogram,
an in-flight gauge, transport payload bytes — and a bounded
**slow-request ring**: a request slower than ``SD_SLOW_REQUEST_MS``
(default 250) keeps its full span tree, so a slow ``search.paths`` shows
its SQL / reader-lock / serialize breakdown instead of just a number.

Each observed request opens a small :class:`~.spans.Trace` that is NOT
put in the job-trace ring (requests are orders of magnitude more
frequent than jobs); the trace only survives if the request crossed the
slow threshold. While the request span is open, ``models/base.query``
sees :func:`spans.current_trace` with ``record_db_spans`` set and nests
one ``db.query`` span per SELECT — the breakdown the ring serves.

Cardinality: ``proc`` is the router's procedure key — a closed set
(~100 keys, fixed at mount). ``outcome`` ∈ {ok, api_error, error, shed}:
``api_error`` is a well-formed 4xx-class rejection (``ApiError``),
``error`` an unexpected 5xx-class crash, and ``shed`` an admission-
control BUSY (``BusyError``, a 429 with retry-after) — kept distinct so
the SLO engine (telemetry/slo.py) can exclude deliberate load shedding
from error ratios. With ``tenant=`` the same observation also lands in
the bounded-cardinality ``sd_rspc_tenant_*`` families (tenant = the
8-hex library-id hash from ``slo.tenant_label``).

Exposure: ``telemetry.requestStats`` (rspc) serves :func:`stats` — the
per-procedure p50/p95/p99 estimates plus the slow ring — and every slow
capture emits an ``rspc.slow`` flight-recorder event, so the live SSE /
``telemetry.watch`` stream narrates slow requests as they happen.

``SD_TELEMETRY=off``: :func:`observed` degrades to a bare call — no
trace, no counters, zero allocation past one global read.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable

from . import counter, event, gauge, histogram
from .registry import REQUEST_BUCKETS, enabled, estimate_quantiles
from .spans import Trace

#: slow-request ring capacity (entries carry full span trees — bounded)
SLOW_RING = 64

_REQUESTS = counter(
    "sd_rspc_requests_total",
    "rspc procedure dispatches by procedure, kind and outcome",
    labels=("proc", "kind", "outcome"))
_SECONDS = histogram(
    "sd_rspc_request_seconds", "rspc dispatch latency per procedure",
    labels=("proc",), buckets=REQUEST_BUCKETS)
_IN_FLIGHT = gauge(
    "sd_rspc_in_flight", "rspc dispatches currently executing")
_PAYLOAD = counter(
    "sd_rspc_payload_bytes_total",
    "transport payload bytes per procedure and direction (in = request "
    "body, out = serialized response)", labels=("proc", "direction"))
_SLOW = counter(
    "sd_rspc_slow_requests_total",
    "requests slower than SD_SLOW_REQUEST_MS (each keeps its span tree "
    "in the slow-request ring)", labels=("proc",))
_P99 = gauge(
    "sd_rspc_request_p99_seconds",
    "estimated p99 of sd_rspc_request_seconds per procedure (published "
    "by the resource-watcher tick; alert target — histograms are not "
    "rule targets)", labels=("proc",))
_T_REQUESTS = counter("sd_rspc_tenant_requests_total",
                      labels=("tenant", "outcome"))
_T_SECONDS = histogram("sd_rspc_tenant_request_seconds",
                       labels=("tenant",), buckets=REQUEST_BUCKETS)

_SLOW_RING: deque[dict[str, Any]] = deque(maxlen=SLOW_RING)
_SLOW_LOCK = threading.Lock()

#: per-proc bucket snapshot at the previous publish_quantiles() tick —
#: the p99 gauge is computed over the WINDOW since then, not process
#: lifetime (a cumulative rank would keep an alert firing for hours
#: after a transient slow episode; window quantiles resolve with it)
_P99_PREV: dict[str, list[int]] = {}
_P99_LOCK = threading.Lock()


def slow_threshold_s() -> float:
    """``SD_SLOW_REQUEST_MS`` in seconds (default 250 ms); re-read per
    request so tests and operators can retune a live process."""
    try:
        return max(0.0, float(os.environ.get("SD_SLOW_REQUEST_MS",
                                             "250"))) / 1000.0
    except ValueError:
        return 0.25


def observed(proc: str, kind: str, fn: Callable[[], Any],
             tenant: str | None = None) -> Any:
    """Run one rspc dispatch under full request telemetry. The router's
    only integration point — transports stay unaware. ``tenant`` (a
    bounded ``slo.tenant_label`` hash) additionally records the dispatch
    in the per-tenant families the SLO engine reads."""
    if not enabled():
        return fn()
    # raw paired series writes, NOT the gated Family.inc: a runtime
    # set_enabled() toggle landing mid-request would otherwise drop one
    # side of the inc/dec pair and skew the gauge forever
    in_flight = _IN_FLIGHT.labels()
    with in_flight._lock:
        in_flight.value += 1.0
    trace = Trace(f"rspc-{uuid.uuid4().hex[:12]}", f"rspc.{proc}")
    #: models/base.query only records db spans for traces that opt in —
    #: job traces must keep their per-batch recording discipline
    trace.record_db_spans = True
    outcome = "ok"
    t0 = time.perf_counter()
    try:
        with trace.span("rspc.resolve"):
            return fn()
    except BaseException as e:
        # classified by name, not import — telemetry must not import the
        # api layer (the no-cycles rule this package is built on).
        # BusyError (an ApiError subclass) is checked first: an
        # admission-control shed is deliberate load management, and the
        # SLO engine excludes the `shed` outcome from error ratios.
        name = type(e).__name__
        outcome = ("shed" if name == "BusyError"
                   else "api_error" if name == "ApiError" else "error")
        raise
    finally:
        duration_s = time.perf_counter() - t0
        with in_flight._lock:
            in_flight.value -= 1.0
        _REQUESTS.inc(proc=proc, kind=kind, outcome=outcome)
        _SECONDS.observe(duration_s, proc=proc)
        if tenant is not None:
            _T_REQUESTS.inc(tenant=tenant, outcome=outcome)
            _T_SECONDS.observe(duration_s, tenant=tenant)
        if duration_s >= slow_threshold_s():
            _capture_slow(proc, kind, outcome, duration_s, trace)


def _capture_slow(proc: str, kind: str, outcome: str, duration_s: float,
                  trace: Trace) -> None:
    _SLOW.inc(proc=proc)
    trace.finish()
    entry = {
        "proc": proc,
        "kind": kind,
        "outcome": outcome,
        "duration_s": round(duration_s, 6),
        "unix": round(time.time(), 3),
        "tree": trace.tree(),
    }
    with _SLOW_LOCK:
        _SLOW_RING.append(entry)
    # narrate on the flight recorder (telemetry.watch / SSE); the tree
    # stays in the ring — events must stay small
    event("rspc.slow", proc=proc, kind=kind, outcome=outcome,
          duration_ms=round(duration_s * 1000.0, 1))


def record_payload(proc: str, bytes_in: int, bytes_out: int) -> None:
    """Transport-side payload accounting (the shell knows wire sizes; an
    in-process resolve never serializes)."""
    if not enabled():
        return
    if bytes_in:
        _PAYLOAD.inc(bytes_in, proc=proc, direction="in")
    if bytes_out:
        _PAYLOAD.inc(bytes_out, proc=proc, direction="out")


def slow_requests(limit: int = SLOW_RING) -> list[dict[str, Any]]:
    """Newest-first slice of the slow-request ring."""
    with _SLOW_LOCK:
        entries = list(_SLOW_RING)
    return list(reversed(entries))[:limit]


def clear_slow_requests() -> None:
    """Drop the ring and the p99 window baseline (telemetry.reset()
    zeroes the histograms — a stale baseline would make the first
    post-reset window read negative)."""
    with _SLOW_LOCK:
        _SLOW_RING.clear()
    with _P99_LOCK:
        _P99_PREV.clear()


def publish_quantiles() -> None:
    """Refresh ``sd_rspc_request_p99_seconds`` per live procedure series
    — called by the resource-watcher tick so the alert evaluator (which
    cannot target histograms) has a gauge. Computed over the WINDOW
    since the previous tick (bucket-count deltas): a cumulative-rank p99
    would pin an alert firing long after a transient slow episode
    drained; an idle window publishes 0 (no data), which resolves it."""
    if not enabled():
        return
    with _P99_LOCK:
        for labels, series in _SECONDS.series_items():
            counts, _total, n = series.read()
            if not n:
                continue
            proc = labels["proc"]
            prev = _P99_PREV.get(proc, [0] * len(counts))
            window = [c - p for c, p in zip(counts, prev)]
            _P99_PREV[proc] = counts
            if sum(window) <= 0:
                _P99.set(0.0, proc=proc)
                continue
            q = estimate_quantiles(_SECONDS.buckets, window, qs=(0.99,))
            _P99.set(round(q[0.99], 6), proc=proc)


def stats(slow_limit: int = 16) -> dict[str, Any]:
    """What ``telemetry.requestStats`` serves: per-procedure latency
    quantile estimates, outcome counts, in-flight, payload totals, and
    the slow-request ring (span trees included)."""
    procedures: dict[str, dict[str, Any]] = {}
    for labels, series in _SECONDS.series_items():
        counts, total, n = series.read()
        q = estimate_quantiles(_SECONDS.buckets, counts)
        procedures[labels["proc"]] = {
            "count": n,
            "total_s": round(total, 6),
            "mean_s": round(total / n, 6) if n else 0.0,
            "p50_s": round(q[0.5], 6),
            "p95_s": round(q[0.95], 6),
            "p99_s": round(q[0.99], 6),
        }
    for labels, value in _REQUESTS.series_items():
        stats_row = procedures.get(labels["proc"])
        if stats_row is None:
            continue
        if labels["outcome"] != "ok":
            stats_row["errors"] = int(stats_row.get("errors", 0)
                                      + value.value)
    for labels, value in _PAYLOAD.series_items():
        stats_row = procedures.get(labels["proc"])
        if stats_row is not None:
            stats_row[f"bytes_{labels['direction']}"] = int(value.value)
    return {
        "enabled": enabled(),
        "in_flight": _IN_FLIGHT.labels().value,
        "slow_threshold_ms": round(slow_threshold_s() * 1000.0, 1),
        "procedures": procedures,
        "slow": slow_requests(slow_limit),
    }
