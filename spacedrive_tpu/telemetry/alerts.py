"""Declarative SLO/alert rules evaluated against the live registry.

Monarch-shaped (PAPERS.md: planet-scale in-memory monitoring): instead of
shipping raw scrapes to an external evaluator, a small ticker inside the
node evaluates **rules** against the in-memory series and fires edges
into the flight-recorder event ring (``alert.firing`` /
``alert.resolved``), the node notification surface, and the
``sd_alerts_firing{rule}`` gauge.

Rule grammar (one dict per rule; see ``AlertRule.from_dict`` /
``default_rules`` and docs/architecture/observability.md):

```
{"name": "sync-peer-lag",          # unique; becomes the {rule=} label
 "kind": "threshold",              # threshold | rate | absence
 "series": "sd_sync_peer_lag_ops", # counter/gauge family (sd_* vocabulary)
 "labels": {"peer": "ab12cd34"},   # optional exact-match filter; omitted
                                   # labels match any series
 "op": "gt",                       # gt | lt   (threshold & rate)
 "value": 500,                     # the threshold
 "for_s": 30,                      # condition must hold this long
 "window_s": 60,                   # rate: increase window (counters)
 "severity": "warning"}            # informational passthrough
```

Semantics:

- **threshold** — fires while any matching series compares true against
  ``value``. ``lt`` rules skip series whose value is 0 (an idle/never-
  touched gauge is "no data", not "below the floor").
- **rate** — per-second increase of the summed matching series over the
  trailing ``window_s``; compares like threshold. For counters.
- **absence** — fires while NO matching series exists (device numbers
  missing, an exporter that never came up). ``for_s`` doubles as the
  boot grace period.

Histogram families are not rule targets (alert on the gauges/counters
derived next to them instead).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from . import METRIC_NAME_RE, event, gauge, series_values

logger = logging.getLogger(__name__)

THRESHOLD = "threshold"
RATE = "rate"
ABSENCE = "absence"

_FIRING = gauge(
    "sd_alerts_firing",
    "1 while the named alert rule is firing (telemetry/alerts.py)",
    labels=("rule",))


class AlertRuleError(ValueError):
    """Malformed rule — raised at declaration, never inside the ticker."""


@dataclass(frozen=True)
class AlertRule:
    name: str
    kind: str
    series: str
    labels: dict[str, str] = field(default_factory=dict)
    op: str = "gt"
    value: float = 0.0
    for_s: float = 0.0
    window_s: float = 60.0
    severity: str = "warning"
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in (THRESHOLD, RATE, ABSENCE):
            raise AlertRuleError(f"{self.name}: unknown kind {self.kind!r}")
        if self.op not in ("gt", "lt"):
            raise AlertRuleError(f"{self.name}: op must be gt|lt")
        if not METRIC_NAME_RE.match(self.series):
            raise AlertRuleError(
                f"{self.name}: series {self.series!r} outside the sd_* "
                "vocabulary")
        if self.for_s < 0 or self.window_s <= 0:
            raise AlertRuleError(f"{self.name}: negative/zero durations")

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "AlertRule":
        try:
            return cls(
                name=str(raw["name"]), kind=str(raw["kind"]),
                series=str(raw["series"]),
                labels={str(k): str(v)
                        for k, v in (raw.get("labels") or {}).items()},
                op=str(raw.get("op", "gt")),
                value=float(raw.get("value", 0.0)),
                for_s=float(raw.get("for_s", 0.0)),
                window_s=float(raw.get("window_s", 60.0)),
                severity=str(raw.get("severity", "warning")),
                description=str(raw.get("description", "")))
        except KeyError as e:
            raise AlertRuleError(f"rule missing {e.args[0]!r}") from None

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "kind": self.kind, "series": self.series,
                "labels": dict(self.labels), "op": self.op,
                "value": self.value, "for_s": self.for_s,
                "window_s": self.window_s, "severity": self.severity,
                "description": self.description}


def default_rules() -> list[AlertRule]:
    """The stock SLO set every node evaluates (override/extend via
    ``SD_ALERT_RULES`` pointing at a JSON list of rule dicts)."""
    return [
        AlertRule(
            name="sync-peer-lag", kind=THRESHOLD,
            series="sd_sync_peer_lag_ops", op="gt", value=500.0, for_s=30.0,
            description="a peer's declared sync backlog stayed above 500 "
                        "ops — ingest is not keeping up with that sender"),
        AlertRule(
            name="quarantine-spike", kind=RATE,
            series="sd_quarantined_files_total", op="gt", value=5.0,
            window_s=30.0, for_s=0.0,
            description="identifier quarantine rate above 5 files/s — a "
                        "location is rotting or a fault storm is live"),
        AlertRule(
            name="scan-rate-floor", kind=THRESHOLD,
            series="sd_scan_files_per_sec", op="lt", value=100.0, for_s=60.0,
            description="the last completed identify pass ran below 100 "
                        "files/s (0 = never scanned, which does not fire)"),
        AlertRule(
            name="device-numbers-missing", kind=ABSENCE,
            series="sd_hash_router_bytes_per_sec",
            labels={"backend": "device"}, for_s=600.0, severity="info",
            description="no device-engine routing rate has ever been "
                        "published — the relay is still down and device "
                        "numbers remain unmeasured"),
        AlertRule(
            name="db-quick-check-failed", kind=THRESHOLD,
            series="sd_boot_integrity_checks_total",
            labels={"outcome": "corrupt"}, op="gt", value=0.0, for_s=0.0,
            severity="critical",
            description="a library DB failed PRAGMA quick_check at boot — "
                        "the repair ladder quarantined it and restored the "
                        "newest backup (or recreated it fresh); inspect "
                        "libraries/quarantine/"),
        AlertRule(
            name="disk-full", kind=RATE,
            series="sd_recovery_disk_full_total", op="gt", value=0.01,
            window_s=60.0, for_s=0.0, severity="critical",
            description="ENOSPC is being absorbed by graceful degradation "
                        "(quarantined gathers, skipped thumbnails, ring-only "
                        "telemetry, paused commits) — free disk space"),
        # serving tier (ISSUE 10): the p99 gauge is published per
        # procedure by the resource-watcher tick (histograms are not rule
        # targets); errors ride the outcome label on the request counter
        AlertRule(
            name="rspc-query-p99", kind=THRESHOLD,
            series="sd_rspc_request_p99_seconds", op="gt", value=2.0,
            for_s=30.0,
            description="a procedure's estimated p99 dispatch latency "
                        "stayed above 2 s — the read path is melting under "
                        "load (check the slow-request ring for the span "
                        "breakdown)"),
        AlertRule(
            name="rspc-error-rate", kind=RATE,
            series="sd_rspc_requests_total",
            labels={"outcome": "error"}, op="gt", value=1.0,
            window_s=60.0, for_s=0.0, severity="critical",
            description="unexpected rspc dispatch failures above 1/s over "
                        "the last minute (api_error rejections do not "
                        "count) — a handler is crashing under traffic"),
    ]


def load_rules() -> list[AlertRule]:
    """default_rules(), or the JSON rule list named by ``SD_ALERT_RULES``
    (a malformed file logs and falls back — alerting must not wedge
    boot)."""
    import json
    import os
    from pathlib import Path

    path = os.environ.get("SD_ALERT_RULES")
    if not path:
        return default_rules()
    try:
        raw = json.loads(Path(path).read_text(encoding="utf-8"))
        return [AlertRule.from_dict(r) for r in raw]
    except Exception:
        logger.exception("SD_ALERT_RULES %r unusable; using defaults", path)
        return default_rules()


class _RuleState:
    __slots__ = ("pending_since", "firing", "value", "labels", "history")

    def __init__(self) -> None:
        self.pending_since: float | None = None
        self.firing = False
        self.value: float | None = None
        self.labels: dict[str, str] | None = None
        #: (t, summed value) samples for rate rules, trimmed to window_s
        self.history: list[tuple[float, float]] = []


class AlertEvaluator:
    """Evaluates the rule set on a ticker thread (or on demand via
    :meth:`evaluate_once` — tests drive it with an injected clock)."""

    def __init__(self, rules: list[AlertRule] | None = None,
                 interval_s: float = 5.0,
                 notify: Callable[[AlertRule, bool, float | None], None]
                 | None = None) -> None:
        self.rules = list(rules if rules is not None else load_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise AlertRuleError(f"duplicate rule names in {names}")
        self.interval_s = interval_s
        self._notify = notify
        self._states = {r.name: _RuleState() for r in self.rules}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "AlertEvaluator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sd-alerts")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                logger.exception("alert evaluation tick failed")

    # -- evaluation ----------------------------------------------------------
    def _matching(self, rule: AlertRule) -> list[tuple[dict[str, str], float]]:
        return [(lbls, v) for lbls, v in series_values(rule.series)
                if all(lbls.get(k) == v for k, v in rule.labels.items())]

    @staticmethod
    def _breach(rule: AlertRule, value: float) -> bool:
        return value > rule.value if rule.op == "gt" else value < rule.value

    def _condition(self, rule: AlertRule, state: _RuleState,
                   now: float) -> tuple[bool, float | None,
                                        dict[str, str] | None]:
        """(condition-true, offending value, offending labels)."""
        matching = self._matching(rule)
        if rule.kind == ABSENCE:
            return (not matching, None, dict(rule.labels) or None)
        if rule.kind == THRESHOLD:
            worst: tuple[float, dict[str, str]] | None = None
            for lbls, v in matching:
                if rule.op == "lt" and v == 0.0:
                    continue  # idle/never-written gauge: no data, no alert
                if self._breach(rule, v) and (
                        worst is None
                        or (v > worst[0] if rule.op == "gt" else v < worst[0])):
                    worst = (v, lbls)
            if worst is None:
                return False, None, None
            return True, worst[0], worst[1]
        # RATE: per-second increase of the summed series over the window
        total = sum(v for _lbls, v in matching)
        if state.history and total < state.history[-1][1]:
            # counter reset (pool/shell restart, telemetry.reset()): every
            # older sample is a stale-high baseline — keeping any would
            # clamp the computed rate to 0 for a full window (max() below)
            # and, worse, the next increments would be measured against
            # the pre-reset total. Start the window over from here.
            state.history.clear()
        state.history.append((now, total))
        floor = now - rule.window_s
        while len(state.history) > 1 and state.history[1][0] <= floor:
            state.history.pop(0)
        t0, v0 = state.history[0]
        if now - t0 <= 0:
            return False, None, None
        per_sec = max(0.0, total - v0) / (now - t0)
        return self._breach(rule, per_sec), round(per_sec, 3), None

    def evaluate_once(self, now: float | None = None) -> list[dict[str, Any]]:
        """One pass over every rule; returns the post-pass state() list.
        ``now`` is injectable so tests drive for_s/window_s without
        sleeping."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for rule in self.rules:
                state = self._states[rule.name]
                cond, value, labels = self._condition(rule, state, now)
                if cond:
                    if state.pending_since is None:
                        state.pending_since = now
                    state.value, state.labels = value, labels
                    held = now - state.pending_since
                    if not state.firing and held >= rule.for_s:
                        state.firing = True
                        self._edge(rule, state, firing=True)
                else:
                    state.pending_since = None
                    state.value, state.labels = value, labels
                    if state.firing:
                        state.firing = False
                        self._edge(rule, state, firing=False)
            return self._state_locked()

    def _edge(self, rule: AlertRule, state: _RuleState, firing: bool) -> None:
        _FIRING.set(1.0 if firing else 0.0, rule=rule.name)
        event("alert.firing" if firing else "alert.resolved",
              rule=rule.name, series=rule.series, severity=rule.severity,
              value=state.value,
              **({"labels": state.labels} if state.labels else {}))
        logger.warning("alert %s %s (series %s, value %s)", rule.name,
                       "FIRING" if firing else "resolved", rule.series,
                       state.value)
        if self._notify is not None:
            try:
                self._notify(rule, firing, state.value)
            except Exception:
                logger.exception("alert notify hook failed for %s", rule.name)

    # -- introspection -------------------------------------------------------
    def _state_locked(self) -> list[dict[str, Any]]:
        out = []
        for rule in self.rules:
            s = self._states[rule.name]
            # "value" stays the CONFIGURED threshold (rule.to_dict());
            # the live observation rides separately — a healthy rule's
            # None observation must not clobber the threshold clients
            # render ("fires above <value>")
            out.append({**rule.to_dict(), "firing": s.firing,
                        "live_value": s.value,
                        "pending": s.pending_since is not None
                        and not s.firing})
        return out

    def state(self) -> list[dict[str, Any]]:
        """What ``telemetry.alerts`` serves: every rule + live status."""
        with self._lock:
            return self._state_locked()
