"""Span-tagged wall-clock sampling profiler + process resource watcher
(ISSUE 10).

:class:`SamplingProfiler` wakes ``SD_PROFILE_HZ`` times per second
(default **off** — nothing starts, zero overhead), snapshots every
thread's stack via ``sys._current_frames()``, and attributes each sample
to the sampled thread's innermost **open span**
(:func:`spans.active_span` — the cross-thread mirror of the span
thread-local). Samples aggregate as folded stacks
``<span>;<frame>;<frame> count`` — the flamegraph input format — keyed
by span name, so "where does wall time go *inside* ``pipeline.hash``"
is one grep. Threads with no open span fold under ``other``.

Export: ``<data_dir>/logs/profiles/<name>.folded`` (plus a
``.traces.json`` sidecar mapping trace ids → per-span sample counts, so
``python -m spacedrive_tpu.telemetry --profile <job_id>`` can answer by
job as well as by span). Both use the tempfile→fsync→rename discipline
(utils/atomic) like the trace JSONL exports beside them.

:class:`ResourceWatcher` is the cheap always-on sibling: a slow ticker
(``SD_RESOURCE_INTERVAL_S``, default 5 s) publishing
``sd_proc_rss_bytes`` / ``sd_proc_open_fds`` / ``sd_proc_threads`` from
/proc, and refreshing the serving-tier p99 gauges
(:func:`requests.publish_quantiles`) the alert rules read.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import Counter
from pathlib import Path
from typing import Any

from . import counter as _counter
from . import gauge as _gauge
from .registry import enabled
from . import spans as _spans

logger = logging.getLogger(__name__)

#: stack depth cap per sample (deep recursion must not bloat keys)
MAX_DEPTH = 48

_SAMPLES = _counter(
    "sd_profile_samples_total",
    "wall-clock profiler samples attributed per active span name "
    "('other' = the sampled thread had no open span)", labels=("span",))

_RSS = _gauge("sd_proc_rss_bytes", "resident set size of this process")
_FDS = _gauge("sd_proc_open_fds", "open file descriptors of this process")
_THREADS = _gauge("sd_proc_threads", "live Python threads in this process")


def profile_hz() -> float:
    """``SD_PROFILE_HZ`` (default 0 = off; clamped to ≤ 1000)."""
    try:
        return min(1000.0, max(0.0, float(
            os.environ.get("SD_PROFILE_HZ", "0"))))
    except ValueError:
        return 0.0


def _fold_frame(frame: Any) -> str:
    """One thread's stack as ``outermost;...;innermost`` frames, each
    ``module:function`` (basename only — paths would bloat every key)."""
    parts: list[str] = []
    while frame is not None and len(parts) < MAX_DEPTH:
        code = frame.f_code
        name = os.path.basename(code.co_filename)
        if name.endswith(".py"):
            name = name[:-3]
        parts.append(f"{name}:{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


class SamplingProfiler:
    """Wall-clock sampler over ``sys._current_frames()``. One instance =
    one aggregation window; ``stop()`` freezes it, ``export()`` writes
    the folded file."""

    def __init__(self, hz: float | None = None) -> None:
        self.hz = profile_hz() if hz is None else hz
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._folded: Counter[str] = Counter()
        self._by_span: Counter[str] = Counter()
        #: trace_id -> span name -> samples (the job-id view)
        self._by_trace: dict[str, Counter[str]] = {}
        self.samples = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SamplingProfiler | None":
        """Start sampling; returns None (and starts nothing) at hz 0 —
        the zero-overhead-when-off contract."""
        if self.hz <= 0:
            return None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sd-profiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        interval = 1.0 / self.hz
        own = threading.get_ident()
        while not self._stop.wait(interval):
            try:
                self._sample_once(own)
            except Exception:  # sampling must never take the process down
                logger.exception("profiler sample failed")

    def _sample_once(self, own_tid: int) -> None:
        frames = sys._current_frames()
        with self._lock:
            for tid, frame in frames.items():
                if tid == own_tid:
                    continue
                active = _spans.active_span(tid)
                span_name = active[1] if active else "other"
                stack = _fold_frame(frame)
                self._folded[f"{span_name};{stack}"] += 1
                self._by_span[span_name] += 1
                if active is not None:
                    self._by_trace.setdefault(
                        active[0], Counter())[span_name] += 1
                self.samples += 1
                _SAMPLES.inc(span=span_name)

    # -- reads ---------------------------------------------------------------
    def totals_by_span(self) -> dict[str, int]:
        with self._lock:
            return dict(self._by_span)

    def totals_by_trace(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {t: dict(c) for t, c in self._by_trace.items()}

    def folded(self, top: int | None = None) -> list[tuple[str, int]]:
        with self._lock:
            rows = self._folded.most_common(top)
        return rows

    # -- export --------------------------------------------------------------
    def export(self, base_dir: str | Path,
               name: str = "profile") -> Path | None:
        """Write the folded aggregation beside the trace exports
        (atomic; best-effort — a full disk degrades like trace export)."""
        if not self.samples:
            return None
        try:
            from ..utils.atomic import atomic_write_text

            out_dir = profiles_dir(base_dir)
            out_dir.mkdir(parents=True, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            path = out_dir / f"{name}-{stamp}.folded"
            with self._lock:
                lines = "".join(f"{key} {count}\n" for key, count
                                in sorted(self._folded.items()))
                sidecar = {t: dict(c) for t, c in self._by_trace.items()}
            atomic_write_text(path, lines)
            import json

            atomic_write_text(path.with_suffix(".traces.json"),
                              json.dumps(sidecar, indent=1, sort_keys=True))
            return path
        except OSError as e:
            import errno as _errno

            if getattr(e, "errno", None) == _errno.ENOSPC:
                from ..recovery import note_disk_full

                note_disk_full("trace_export")
            logger.exception("could not export profile (aggregation stays "
                             "in memory)")
            return None


def profiles_dir(base_dir: str | Path) -> Path:
    return Path(base_dir) / "logs" / "profiles"


def load_folded(base_dir: str | Path) -> Counter:
    """Merge every exported ``.folded`` file under ``base_dir`` — the
    CLI's ``--profile`` read path."""
    merged: Counter[str] = Counter()
    for path in sorted(profiles_dir(base_dir).glob("*.folded")):
        try:
            for line in path.read_text().splitlines():
                key, _, count = line.rpartition(" ")
                if key and count.isdigit():
                    merged[key] += int(count)
        except OSError:
            continue
    return merged


def load_trace_totals(base_dir: str | Path) -> dict[str, dict[str, int]]:
    """Merge every ``.traces.json`` sidecar (trace id → span → samples)."""
    import json

    merged: dict[str, dict[str, int]] = {}
    for path in sorted(profiles_dir(base_dir).glob("*.traces.json")):
        try:
            data = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(data, dict):
            continue
        for trace_id, by_span in data.items():
            if not isinstance(by_span, dict):
                continue
            agg = merged.setdefault(trace_id, {})
            for span, n in by_span.items():
                agg[span] = agg.get(span, 0) + int(n)
    return merged


# -- process resource watcher --------------------------------------------------

def _read_proc_status() -> tuple[float, float]:
    """(rss_bytes, 0.0-placeholder) from /proc/self/status; (0, 0) when
    /proc is unavailable (non-Linux test hosts)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0, 0.0
    except OSError:
        pass
    return 0.0, 0.0


def _count_fds() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


class ResourceWatcher:
    """Slow ticker publishing process gauges + serving-tier quantile
    gauges. One per Node (started at boot, stopped at shutdown), like the
    alert evaluator."""

    def __init__(self, interval_s: float | None = None) -> None:
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get("SD_RESOURCE_INTERVAL_S", "5"))
            except ValueError:
                interval_s = 5.0
        self.interval_s = max(0.05, interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ResourceWatcher":
        self.tick()  # gauges live from boot, not after the first interval
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sd-resources")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("resource watcher tick failed")

    def tick(self) -> None:
        if not enabled():
            return
        rss, _ = _read_proc_status()
        _RSS.set(rss)
        _FDS.set(_count_fds())
        _THREADS.set(float(threading.active_count()))
        from . import requests as _requests

        _requests.publish_quantiles()
