"""Lock-cheap metrics registry: counters, gauges, histograms; labeled
families; Prometheus text exposition.

Design constraints (ISSUE 5 tentpole):

- **Always-on but cheap**: the hot paths (pipeline stages, hash dispatch,
  retry backoff) record per-*batch*, never per-file, and every record call
  starts with one module-global read — with ``SD_TELEMETRY=off`` nothing
  past that read runs (no lock, no allocation, no dict walk).
- **Fixed vocabulary**: metric names must match ``^sd_[a-z0-9_]+$`` (the
  ``telemetry-discipline`` sdlint pass enforces this at call sites too)
  and histogram bucket boundaries are fixed at family creation, so a
  scrape series never changes shape mid-process.
- **Labeled families**: one family per metric name; series are keyed by
  the label-value tuple in declaration order. Label cardinality is the
  caller's responsibility — the instrumented code only uses small closed
  sets (stage, lane, backend, status, seam:kind).

Thread-safety: family lookup/creation takes the registry lock (rare —
call sites memoize the family at module import); each series carries its
own small lock for the increment (float ``+=`` is not atomic under the
GIL). A scrape renders from a consistent point-in-time copy per series,
not a global stop-the-world.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
from typing import Any, Iterable

from ..utils.locks import SdLock

#: the one metric-name vocabulary (sdlint telemetry-discipline enforces it)
METRIC_NAME_RE = re.compile(r"^sd_[a-z0-9_]+$")
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

#: latency-shaped default buckets (seconds): sub-ms queue pops up to the
#: multi-minute scan wall clocks this system actually produces
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

#: serving-shaped buckets (ISSUE 10): finer at the low end, capped at the
#: 30 s a client would ever wait. THE one definition — _declare_core,
#: telemetry/requests.py and server/shell.py all declare their request
#: histograms from this constant (a drifted copy would raise the
#: fixed-boundary re-declaration error at import)
REQUEST_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: lock-shaped buckets (ISSUE 14): sanitized-lock waits/holds live in the
#: µs band, with the multi-second tail being exactly the convoy a soak
#: needs to see. THE one definition — _declare_core and utils/locks.py
#: both declare the sd_lock_* histograms from this constant
LOCK_BUCKETS = (0.00001, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1,
                0.5, 2.5)


def _env_enabled() -> bool:
    return os.environ.get("SD_TELEMETRY", "on").strip().lower() not in (
        "0", "off", "false", "no")


#: the one global the fast path reads; default ON (the overhead gate in
#: bench.py keeps it honest)
_ENABLED = _env_enabled()


def enabled() -> bool:
    return _ENABLED


def set_enabled(value: bool) -> None:
    """Runtime toggle — the bench's same-session A/B and tests use this;
    production processes set ``SD_TELEMETRY`` before start instead."""
    global _ENABLED
    _ENABLED = bool(value)


def reload_enabled() -> bool:
    """Re-read ``SD_TELEMETRY`` after an in-process env change."""
    set_enabled(_env_enabled())
    return _ENABLED


# -- series types --------------------------------------------------------------

class Counter:
    """Monotonically increasing float."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _ENABLED:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Gauge:
    """Last-written value."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram:
    """Fixed-boundary histogram: per-bucket counts + sum + count."""

    __slots__ = ("_lock", "boundaries", "bucket_counts", "sum", "count")

    def __init__(self, boundaries: tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self.boundaries = boundaries
        self.bucket_counts = [0] * (len(boundaries) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            self.bucket_counts[idx] += 1
            self.sum += value
            self.count += 1

    def read(self) -> tuple[list[int], float, int]:
        """Consistent (bucket_counts, sum, count) under the series lock —
        a scrape racing an observe() must never emit a histogram whose
        cumulative +Inf bucket disagrees with its _count line."""
        with self._lock:
            return list(self.bucket_counts), self.sum, self.count


_SERIES_TYPES = {COUNTER: Counter, GAUGE: Gauge, HISTOGRAM: Histogram}


class Family:
    """One named metric: a set of series keyed by label values."""

    def __init__(self, name: str, help_text: str, typ: str,
                 label_names: tuple[str, ...],
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self.name = name
        self.help = help_text
        self.type = typ
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets)) if typ == HISTOGRAM else ()
        # the per-SERIES locks below stay raw threading.Locks: they are
        # per-instance data-cell latches on the hottest path in the
        # process, and under the sanitizer they are exactly where its own
        # bookkeeping re-enters (the busy-flag bypass in utils/locks).
        # The family/registry structure locks are the shared-state ones.
        self._lock = SdLock("telemetry.family")
        self._series: dict[tuple[str, ...], Any] = {}
        if not label_names:
            # label-less families expose their zero sample immediately, so
            # a scrape always shows the full vocabulary
            self._series[()] = self._new_series()

    def _new_series(self) -> Any:
        if self.type == HISTOGRAM:
            return Histogram(self.buckets)
        return _SERIES_TYPES[self.type]()

    def labels(self, **label_values: str) -> Any:
        """Resolve (create if needed) the series for these label values.
        Call sites on hot paths memoize the returned series."""
        if set(label_values) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(sorted(label_values))}")
        key = tuple(str(label_values[n]) for n in self.label_names)
        series = self._series.get(key)
        if series is None:
            with self._lock:
                series = self._series.setdefault(key, self._new_series())
        return series

    # -- label-aware conveniences (gated before any dict work) ---------------
    def inc(self, amount: float = 1.0, **label_values: str) -> None:
        if not _ENABLED:
            return
        self.labels(**label_values).inc(amount)

    def set(self, value: float, **label_values: str) -> None:
        if not _ENABLED:
            return
        self.labels(**label_values).set(value)

    def dec(self, amount: float = 1.0, **label_values: str) -> None:
        if not _ENABLED:
            return
        self.labels(**label_values).dec(amount)

    def observe(self, value: float, **label_values: str) -> None:
        if not _ENABLED:
            return
        self.labels(**label_values).observe(value)

    # -- introspection -------------------------------------------------------
    def series_items(self) -> list[tuple[dict[str, str], Any]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(zip(self.label_names, key)), s) for key, s in items]

    def _reset(self) -> None:
        with self._lock:
            self._series = {}
            if not self.label_names:
                self._series[()] = self._new_series()


class Registry:
    """All families of one process; the scrape and snapshot surface."""

    def __init__(self) -> None:
        self._lock = SdLock("telemetry.registry")
        self._families: dict[str, Family] = {}

    # -- declaration ---------------------------------------------------------
    def _family(self, name: str, help_text: str, typ: str,
                labels: Iterable[str],
                buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Family:
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {METRIC_NAME_RE.pattern}")
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"{name}: bad label name {ln!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, help_text, typ, label_names, buckets)
                self._families[name] = fam
                return fam
        if fam.type != typ or fam.label_names != label_names:
            raise ValueError(
                f"metric {name} re-declared as {typ}{label_names} "
                f"(was {fam.type}{fam.label_names})")
        if typ == HISTOGRAM and fam.buckets != tuple(sorted(buckets)):
            # fixed-boundary contract: observations silently landing in
            # someone else's buckets is exactly the shape drift this
            # registry exists to prevent
            raise ValueError(
                f"histogram {name} re-declared with different buckets")
        return fam

    def counter(self, name: str, help_text: str = "",
                labels: Iterable[str] = ()) -> Family:
        return self._family(name, help_text, COUNTER, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Iterable[str] = ()) -> Family:
        return self._family(name, help_text, GAUGE, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Iterable[str] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Family:
        return self._family(name, help_text, HISTOGRAM, labels, buckets)

    # -- reads ---------------------------------------------------------------
    def families(self) -> list[Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def value(self, name: str, **label_values: str) -> float:
        """Point value of a counter/gauge series (0.0 when absent) — the
        bench's before/after deltas read through this."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None or fam.type == HISTOGRAM:
            return 0.0
        key = tuple(str(label_values.get(n, "")) for n in fam.label_names)
        series = fam._series.get(key)
        return series.value if series is not None else 0.0

    def series_values(self, name: str) -> list[tuple[dict[str, str], float]]:
        """Every (labels, value) of a counter/gauge family."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None or fam.type == HISTOGRAM:
            return []
        return [(lbls, s.value) for lbls, s in fam.series_items()]

    def snapshot(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for fam in self.families():
            series = []
            for lbls, s in fam.series_items():
                if fam.type == HISTOGRAM:
                    counts, total, n = s.read()
                    series.append({"labels": lbls, "count": n,
                                   "sum": round(total, 6),
                                   "buckets": dict(zip(
                                       [str(b) for b in fam.buckets] + ["+Inf"],
                                       counts))})
                else:
                    series.append({"labels": lbls, "value": s.value})
            out[fam.name] = {"type": fam.type, "help": fam.help,
                             "series": series}
        return out

    # -- Prometheus text exposition (format 0.0.4) --------------------------
    def render_prometheus(self) -> str:
        lines: list[str] = []
        for fam in self.families():
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.type}")
            for lbls, s in fam.series_items():
                if fam.type == HISTOGRAM:
                    counts, total, n = s.read()
                    cumulative = 0
                    for bound, c in zip(fam.buckets, counts):
                        cumulative += c
                        lines.append(_sample(f"{fam.name}_bucket",
                                             {**lbls, "le": _fmt(bound)},
                                             cumulative))
                    cumulative += counts[-1]
                    lines.append(_sample(f"{fam.name}_bucket",
                                         {**lbls, "le": "+Inf"}, cumulative))
                    lines.append(_sample(f"{fam.name}_sum", lbls, total))
                    lines.append(_sample(f"{fam.name}_count", lbls, n))
                else:
                    lines.append(_sample(fam.name, lbls, s.value))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every series and drop labeled ones (tests; families stay
        declared so the vocabulary survives)."""
        for fam in self.families():
            fam._reset()


def estimate_quantiles(boundaries: tuple[float, ...],
                       bucket_counts: list[int],
                       qs: tuple[float, ...] = (0.5, 0.95, 0.99),
                       ) -> dict[float, float]:
    """Classic Prometheus-style quantile estimate from fixed buckets:
    linear interpolation inside the bucket the target rank lands in. The
    +Inf bucket clamps to the last finite boundary (the estimate cannot
    exceed what the buckets resolve). Zero observations → all zeros."""
    total = sum(bucket_counts)
    out: dict[float, float] = {}
    if total == 0:
        return {q: 0.0 for q in qs}
    for q in qs:
        target = q * total
        cum = 0
        lo = 0.0
        value = boundaries[-1] if boundaries else 0.0
        for i, count in enumerate(bucket_counts):
            hi = boundaries[i] if i < len(boundaries) else boundaries[-1]
            if count and cum + count >= target:
                value = lo + (hi - lo) * ((target - cum) / count)
                break
            cum += count
            lo = hi
        out[q] = value
    return out


def _fmt(value: float) -> str:
    return repr(value) if value != int(value) else str(int(value))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _sample(name: str, labels: dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in labels.items())
        name = f"{name}{{{inner}}}"
    if isinstance(value, float) and value == int(value):
        return f"{name} {int(value)}"
    return f"{name} {value}"
