"""Declarative SLO engine over the serving-tier request telemetry.

ISSUE 20 tentpole. PR 10 gave every rspc dispatch latency histograms and
outcome counters; this module turns them into **objectives**: "99% of
requests (for a proc, or a tenant class) complete under X seconds,
measured over a budget window". Each objective maintains:

- the **SLI**: good requests / valid requests, read straight from the
  cumulative ``sd_rspc_request_seconds`` buckets (good = under the
  latency threshold and not an unexpected error) and
  ``sd_rspc_requests_total`` outcomes. BUSY sheds (admission control,
  outcome ``shed``) are *excluded from the valid set entirely* — a shed
  is deliberate load management with an explicit retry-after, not a
  broken promise, and counting it as an error would make admission
  control look like an outage.
- **error-budget remaining** over the objective's budget window
  (1.0 = untouched, 0.0 = exhausted), published as
  ``sd_slo_budget_remaining{objective}``.
- **multi-window burn rates** (the Google SRE fast/slow pairs, default
  5m/1h and 30m/6h), published as
  ``sd_slo_burn_rate{objective, window}``. A pair fires only when BOTH
  its windows burn above the pair's threshold (AND-gating: the short
  window proves it is happening *now*, the long window proves it is not
  a blip), emitting ``slo.burn`` flight-recorder events on both edges —
  which ride the existing event ring → SSE / ``telemetry.watch`` / CLI
  ``--follow`` plumbing for free.

Per-tenant SLIs read the bounded-cardinality ``sd_rspc_tenant_*``
families, labeled by :func:`tenant_label` — an 8-hex library-id hash in
the ``mesh.peer_label`` mold, LRU-capped at ``SD_TENANT_LABEL_CAP``
distinct tenants with an ``other`` overflow label, so a million
libraries can never explode the registry.

The engine mirrors :class:`~.alerts.AlertEvaluator`: a ticker thread in
production, :meth:`evaluate_once(now=...)` with an injected clock in
tests — burn-rate math over hours runs in microseconds on a virtual
clock. Like the alert evaluator's rate rules, cumulative samples that
*decrease* (a registry reset) restart the window instead of poisoning
it with a stale baseline.

Objectives load from ``SD_SLO_OBJECTIVES`` (a JSON list of objective
dicts) or fall back to :func:`default_objectives`. Served by the rspc
``telemetry.sloStatus`` query and rendered by
``python -m spacedrive_tpu.telemetry --slo``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

from . import counter, event, gauge, histogram
from .registry import REQUEST_BUCKETS

logger = logging.getLogger(__name__)

# -- bounded tenant labels -----------------------------------------------------

#: distinct tenant hashes the registry will ever carry; everything past
#: the cap shares the ``other`` series (re-read per miss so tests can
#: retune; the assigned map itself is what bounds the registry)
_TENANT_CAP_DEFAULT = 64
OTHER_TENANT = "other"
LOCAL_TENANT = "local"

_TENANT_LOCK = threading.Lock()
#: tenant id -> 8-hex label, LRU-ordered (hot tenants stay introspectable
#: at the front of status dumps); insertion stops at the cap — an already
#: -assigned tenant keeps its label forever, so the registry's tenant
#: cardinality is hard-bounded at cap + 2 (``other`` + ``local``)
_TENANT_LRU: OrderedDict[str, str] = OrderedDict()


def _tenant_cap() -> int:
    import os

    try:
        return max(1, int(os.environ.get("SD_TENANT_LABEL_CAP",
                                         str(_TENANT_CAP_DEFAULT))))
    except ValueError:
        return _TENANT_CAP_DEFAULT


def tenant_label(library_id: str | None) -> str:
    """Bounded tenant label for a library id: 8 hex chars of blake2s
    (``mesh.peer_label``-style), ``local`` for node-scoped dispatches,
    ``other`` once ``SD_TENANT_LABEL_CAP`` distinct tenants are live."""
    if not library_id:
        return LOCAL_TENANT
    with _TENANT_LOCK:
        label = _TENANT_LRU.get(library_id)
        if label is not None:
            _TENANT_LRU.move_to_end(library_id)
            return label
        if len(_TENANT_LRU) >= _tenant_cap():
            return OTHER_TENANT
        import hashlib

        label = hashlib.blake2s(library_id.encode("utf-8", "replace"),
                                digest_size=4).hexdigest()
        _TENANT_LRU[library_id] = label
        return label


def reset_tenant_labels() -> None:
    """Tests: forget every assigned tenant (telemetry.reset() companion)."""
    with _TENANT_LOCK:
        _TENANT_LRU.clear()


def tenant_labels() -> list[str]:
    """Live tenant labels, most-recently-used last (introspection)."""
    with _TENANT_LOCK:
        return list(_TENANT_LRU.values())


# -- module metric handles -----------------------------------------------------
# families (help text, the single copy) are declared in _declare_core;
# these are get-or-create lookups exactly like server/pool.py's

_REQUESTS = counter("sd_rspc_requests_total",
                    labels=("proc", "kind", "outcome"))
_SECONDS = histogram("sd_rspc_request_seconds", labels=("proc",),
                     buckets=REQUEST_BUCKETS)
_T_REQUESTS = counter("sd_rspc_tenant_requests_total",
                      labels=("tenant", "outcome"))
_T_SECONDS = histogram("sd_rspc_tenant_request_seconds", labels=("tenant",),
                       buckets=REQUEST_BUCKETS)
_BUDGET = gauge("sd_slo_budget_remaining", labels=("objective",))
_BURN = gauge("sd_slo_burn_rate", labels=("objective", "window"))


# -- objectives ----------------------------------------------------------------

class SloObjectiveError(ValueError):
    """Malformed objective — raised at declaration, never in the ticker."""


def _window_label(seconds: float) -> str:
    if seconds % 3600 == 0 and seconds >= 3600:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0 and seconds >= 60:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


@dataclass(frozen=True)
class SloObjective:
    """One objective: {proc or tenant-class, latency threshold, target
    ratio, budget window}. ``proc=None, tenant=None`` covers every
    dispatch; ``tenant="*"`` aggregates the per-tenant families (so the
    per-tenant recording path itself is under an SLO); ``tenant="<8hex>"``
    pins one tenant class."""

    name: str
    threshold_s: float
    target: float
    window_s: float = 6 * 3600.0
    proc: str | None = None
    tenant: str | None = None
    #: (short, long) burn windows; a pair fires only when BOTH exceed
    #: its threshold (AND-gating)
    fast_windows: tuple[float, float] = (300.0, 3600.0)
    slow_windows: tuple[float, float] = (1800.0, 21600.0)
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    severity: str = "warning"
    description: str = ""

    def __post_init__(self) -> None:
        if self.threshold_s <= 0:
            raise SloObjectiveError(f"{self.name}: threshold_s must be > 0")
        if not 0.0 < self.target < 1.0:
            raise SloObjectiveError(f"{self.name}: target must be in (0, 1)")
        if self.window_s <= 0:
            raise SloObjectiveError(f"{self.name}: window_s must be > 0")
        for pair in (self.fast_windows, self.slow_windows):
            if len(pair) != 2 or pair[0] <= 0 or pair[1] <= pair[0]:
                raise SloObjectiveError(
                    f"{self.name}: burn windows must be (short, long) with "
                    f"0 < short < long, got {pair}")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise SloObjectiveError(f"{self.name}: burn thresholds must "
                                    "be > 0")
        if self.proc is not None and self.tenant is not None:
            raise SloObjectiveError(
                f"{self.name}: proc and tenant filters are exclusive (one "
                "objective reads one family)")

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "SloObjective":
        try:
            return cls(
                name=str(raw["name"]),
                threshold_s=float(raw["threshold_s"]),
                target=float(raw["target"]),
                window_s=float(raw.get("window_s", 6 * 3600.0)),
                proc=(str(raw["proc"]) if raw.get("proc") else None),
                tenant=(str(raw["tenant"]) if raw.get("tenant") else None),
                fast_windows=tuple(float(w) for w in raw.get(
                    "fast_windows", (300.0, 3600.0))),
                slow_windows=tuple(float(w) for w in raw.get(
                    "slow_windows", (1800.0, 21600.0))),
                fast_burn=float(raw.get("fast_burn", 14.4)),
                slow_burn=float(raw.get("slow_burn", 6.0)),
                severity=str(raw.get("severity", "warning")),
                description=str(raw.get("description", "")))
        except KeyError as e:
            raise SloObjectiveError(
                f"objective missing {e.args[0]!r}") from None

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "threshold_s": self.threshold_s,
                "target": self.target, "window_s": self.window_s,
                "proc": self.proc, "tenant": self.tenant,
                "fast_windows": list(self.fast_windows),
                "slow_windows": list(self.slow_windows),
                "fast_burn": self.fast_burn, "slow_burn": self.slow_burn,
                "severity": self.severity, "description": self.description}


def default_objectives() -> list[SloObjective]:
    """The stock serving objectives (override via ``SD_SLO_OBJECTIVES``)."""
    return [
        SloObjective(
            name="queries-fast", threshold_s=0.25, target=0.99,
            window_s=6 * 3600.0,
            description="99% of rspc dispatches complete under 250 ms "
                        "(the slow-request threshold) over 6 h — the "
                        "whole-node read-path promise"),
        SloObjective(
            name="tenant-reads", tenant="*", threshold_s=1.0, target=0.995,
            window_s=6 * 3600.0,
            description="99.5% of library-scoped dispatches across every "
                        "tenant complete under 1 s over 6 h — the "
                        "multi-tenant fairness promise (sheds excluded; "
                        "admission control is not an outage)"),
    ]


def load_objectives() -> list[SloObjective]:
    """default_objectives(), or the JSON list named by
    ``SD_SLO_OBJECTIVES`` (malformed file logs and falls back — SLO
    evaluation must not wedge boot)."""
    import json
    import os
    from pathlib import Path

    path = os.environ.get("SD_SLO_OBJECTIVES")
    if not path:
        return default_objectives()
    try:
        raw = json.loads(Path(path).read_text(encoding="utf-8"))
        return [SloObjective.from_dict(o) for o in raw]
    except Exception:
        logger.exception("SD_SLO_OBJECTIVES %r unusable; using defaults",
                         path)
        return default_objectives()


# -- engine --------------------------------------------------------------------

class _ObjectiveState:
    __slots__ = ("history", "firing", "budget_remaining", "burn", "sli",
                 "good", "valid")

    def __init__(self) -> None:
        #: (t, cumulative good, cumulative valid) samples, trimmed to the
        #: longest window the objective reads
        self.history: list[tuple[float, float, float]] = []
        #: pair name ("fast" | "slow") -> currently firing
        self.firing: dict[str, bool] = {"fast": False, "slow": False}
        self.budget_remaining = 1.0
        self.burn: dict[str, float] = {}
        self.sli = 1.0
        self.good = 0.0
        self.valid = 0.0


class SloEngine:
    """Evaluates the objective set on a ticker thread (or on demand via
    :meth:`evaluate_once` — tests drive it with an injected clock, the
    same contract as :class:`~.alerts.AlertEvaluator`)."""

    def __init__(self, objectives: list[SloObjective] | None = None,
                 interval_s: float = 5.0) -> None:
        self.objectives = list(objectives if objectives is not None
                               else load_objectives())
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise SloObjectiveError(f"duplicate objective names in {names}")
        self.interval_s = interval_s
        self._states = {o.name: _ObjectiveState() for o in self.objectives}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SloEngine":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sd-slo")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                logger.exception("SLO evaluation tick failed")

    # -- SLI reads -----------------------------------------------------------
    @staticmethod
    def _good_under(seconds_family, threshold_s: float,
                    label: str, want: str | None) -> tuple[float, float]:
        """(under-threshold count, total count) summed over the family's
        series matching the ``label == want`` filter (``want=None`` or
        ``"*"`` matches all)."""
        under = total = 0.0
        boundaries = seconds_family.buckets
        for lbls, series in seconds_family.series_items():
            if want not in (None, "*") and lbls.get(label) != want:
                continue
            counts, _sum, n = series.read()
            total += n
            for i, bound in enumerate(boundaries):
                if bound <= threshold_s:
                    under += counts[i]
                else:
                    break
        return under, total

    @staticmethod
    def _outcomes(requests_family, label: str,
                  want: str | None) -> dict[str, float]:
        out: dict[str, float] = {}
        for lbls, series in requests_family.series_items():
            if want not in (None, "*") and lbls.get(label) != want:
                continue
            outcome = lbls.get("outcome", "")
            out[outcome] = out.get(outcome, 0.0) + series.value
        return out

    def _totals(self, obj: SloObjective) -> tuple[float, float]:
        """Cumulative (good, valid) for one objective. Sheds leave the
        valid set; unexpected errors leave the good set (conservatively
        assumed fast — a crash that was also slow cannot double-count)."""
        if obj.tenant is not None:
            under, total = self._good_under(_T_SECONDS, obj.threshold_s,
                                            "tenant", obj.tenant)
            outcomes = self._outcomes(_T_REQUESTS, "tenant", obj.tenant)
        else:
            under, total = self._good_under(_SECONDS, obj.threshold_s,
                                            "proc", obj.proc)
            outcomes = self._outcomes(_REQUESTS, "proc", obj.proc)
        sheds = outcomes.get("shed", 0.0)
        errors = outcomes.get("error", 0.0)
        valid = max(0.0, total - sheds)
        good = max(0.0, min(valid, under - sheds - errors))
        return good, valid

    # -- evaluation ----------------------------------------------------------
    @staticmethod
    def _window_delta(history: list[tuple[float, float, float]],
                      now: float, window_s: float) -> tuple[float, float]:
        """(bad, valid) accumulated over the trailing window: newest
        sample minus the newest sample at-or-before ``now - window_s``
        (the oldest retained sample when the process is younger than the
        window — a young window burns conservatively hot, never cold)."""
        if not history:
            return 0.0, 0.0
        floor = now - window_s
        base = history[0]
        for sample in history:
            if sample[0] <= floor:
                base = sample
            else:
                break
        _t1, good1, valid1 = history[-1]
        _t0, good0, valid0 = base
        valid_w = max(0.0, valid1 - valid0)
        bad_w = max(0.0, valid_w - max(0.0, good1 - good0))
        return bad_w, valid_w

    def evaluate_once(self, now: float | None = None) -> list[dict[str, Any]]:
        """One pass over every objective; returns the post-pass status()
        list. ``now`` is injectable so tests drive hour-long burn windows
        without sleeping."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for obj in self.objectives:
                self._evaluate_objective(obj, self._states[obj.name], now)
            return self._status_locked()

    def _evaluate_objective(self, obj: SloObjective, state: _ObjectiveState,
                            now: float) -> None:
        good, valid = self._totals(obj)
        if state.history and (good < state.history[-1][1]
                              or valid < state.history[-1][2]):
            # cumulative counts went DOWN: the registry was reset (tests,
            # pool/shell restart) — a stale baseline would smear phantom
            # burn over a full window, so the window restarts here (the
            # same discipline as the alert evaluator's rate history)
            state.history.clear()
        state.history.append((now, good, valid))
        horizon = max(obj.window_s, obj.fast_windows[1], obj.slow_windows[1])
        floor = now - horizon
        # keep one sample at-or-before the floor as the window baseline
        while len(state.history) > 1 and state.history[1][0] <= floor:
            state.history.pop(0)

        state.good, state.valid = good, valid
        state.sli = (good / valid) if valid > 0 else 1.0
        budget_fraction = 1.0 - obj.target

        bad_bw, valid_bw = self._window_delta(state.history, now,
                                              obj.window_s)
        if valid_bw > 0:
            consumed = (bad_bw / valid_bw) / budget_fraction
            state.budget_remaining = max(0.0, 1.0 - consumed)
        else:
            state.budget_remaining = 1.0
        _BUDGET.set(round(state.budget_remaining, 6), objective=obj.name)

        burns: dict[str, float] = {}
        for window_s in (*obj.fast_windows, *obj.slow_windows):
            bad_w, valid_w = self._window_delta(state.history, now, window_s)
            rate = ((bad_w / valid_w) / budget_fraction
                    if valid_w > 0 else 0.0)
            label = _window_label(window_s)
            burns[label] = round(rate, 4)
            _BURN.set(burns[label], objective=obj.name, window=label)
        state.burn = burns

        for pair, windows, threshold in (
                ("fast", obj.fast_windows, obj.fast_burn),
                ("slow", obj.slow_windows, obj.slow_burn)):
            labels = tuple(_window_label(w) for w in windows)
            # AND-gate: BOTH windows must burn above the pair threshold
            firing = all(burns[lb] > threshold for lb in labels)
            if firing != state.firing[pair]:
                state.firing[pair] = firing
                event("slo.burn", objective=obj.name, pair=pair,
                      state="firing" if firing else "resolved",
                      windows=list(labels),
                      burn={lb: burns[lb] for lb in labels},
                      threshold=threshold, severity=obj.severity,
                      budget_remaining=round(state.budget_remaining, 4))
                logger.warning(
                    "SLO %s %s burn %s (windows %s, burn %s > %s, budget "
                    "%.1f%% left)", obj.name, pair,
                    "FIRING" if firing else "resolved", labels,
                    {lb: burns[lb] for lb in labels}, threshold,
                    state.budget_remaining * 100.0)

    # -- introspection -------------------------------------------------------
    def _status_locked(self) -> list[dict[str, Any]]:
        out = []
        for obj in self.objectives:
            s = self._states[obj.name]
            out.append({
                **obj.to_dict(),
                "sli": round(s.sli, 6),
                "good": s.good,
                "valid": s.valid,
                "budget_remaining": round(s.budget_remaining, 6),
                "burn": dict(s.burn),
                "firing": dict(s.firing),
            })
        return out

    def status(self) -> list[dict[str, Any]]:
        """What ``telemetry.sloStatus`` serves: every objective with its
        live SLI, budget, burn rates and firing pairs."""
        with self._lock:
            return self._status_locked()
