"""``python -m spacedrive_tpu.telemetry`` — pretty-print a snapshot.

Default: render this process's own registry (useful after driving work
in-process, or to verify the vocabulary). Against a running shell:

    python -m spacedrive_tpu.telemetry --url http://127.0.0.1:8080
    python -m spacedrive_tpu.telemetry --url ... --job <job_id>
    python -m spacedrive_tpu.telemetry --url ... --slo
    python -m spacedrive_tpu.telemetry --prometheus

``--url`` fetches ``telemetry.snapshot`` (or ``telemetry.jobTrace``) over
the rspc HTTP surface; ``--prometheus`` prints the raw text exposition
instead of the table form.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import urllib.error
import urllib.request
from typing import Any


def _headers(auth: str | None) -> dict[str, str]:
    headers = {"content-type": "application/json"}
    if auth:
        headers["Authorization"] = (
            "Basic " + base64.b64encode(auth.encode()).decode())
    return headers


def _fetch(url: str, key: str, arg: Any = None,
           auth: str | None = None) -> Any:
    req = urllib.request.Request(
        f"{url.rstrip('/')}/rspc/{key}",
        data=json.dumps({"arg": arg}).encode(),
        headers=_headers(auth), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        # the shell wraps rspc/auth errors as 4xx JSON bodies — surface
        # the message, not a urllib traceback
        try:
            detail = json.loads(e.read().decode()).get("error", str(e))
        except Exception:
            detail = str(e)
        raise SystemExit(f"{key}: {detail}")
    if "error" in body:
        raise SystemExit(f"{key}: {body['error']}")
    return body["result"]


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in labels.items()) + "}"


def _fmt_value(v: Any) -> str:
    # defensive: a snapshot can carry inf/NaN gauges (a rate computed over
    # a zero interval) or a missing value — the pretty-printer must render
    # them, never raise (int(inf) is an OverflowError)
    try:
        if isinstance(v, float) and v == int(v):
            return str(int(v))
    except (OverflowError, ValueError):
        return str(v)
    if not isinstance(v, (int, float)):
        return str(v)
    return f"{v:.6g}"


def _bucket_quantiles(buckets: dict[str, Any]) -> dict[float, float] | None:
    """p50/p95/p99 estimates from a snapshot's bucket dict (keys are the
    stringified boundaries + '+Inf'); None when the shape is unusable —
    the pretty-printer must render any snapshot, never raise."""
    try:
        pairs = sorted((float(k), int(v)) for k, v in buckets.items()
                       if k != "+Inf")
        if not pairs:
            return None
        boundaries = tuple(b for b, _ in pairs)
        counts = [c for _, c in pairs] + [int(buckets.get("+Inf", 0))]
        if sum(counts) == 0:
            return None
        from .registry import estimate_quantiles

        return estimate_quantiles(boundaries, counts)
    except (TypeError, ValueError):
        return None


def print_snapshot(snap: dict[str, Any], out=None) -> None:
    # out resolved at CALL time, never bound at import (a def-time
    # sys.stdout freezes whatever stream was active when this module
    # first imported — pytest capture objects die between tests)
    out = out if out is not None else sys.stdout
    print(f"telemetry {'ENABLED' if snap.get('enabled') else 'OFF'}",
          file=out)
    metrics = snap.get("metrics", {})
    for name in sorted(metrics):
        fam = metrics[name]
        series = fam.get("series") or []
        print(f"\n{name} ({fam.get('type', '?')})"
              + (f" — {fam['help']}" if fam.get("help") else ""), file=out)
        if not series:
            # a labeled family after a registry reset has a declared name
            # but no live series — render it empty rather than skipping
            # (the catalogue stays visible) and never raise on it
            print("  (no live series)", file=out)
            continue
        for s in series:
            lbl = _fmt_labels(s.get("labels", {}))
            if fam.get("type") == "histogram":
                count = s.get("count", 0)
                total = s.get("sum", 0.0)
                mean = total / count if count else 0.0
                quantiles = ""
                q = _bucket_quantiles(s.get("buckets") or {})
                if q is not None:
                    quantiles = (f" p50={q[0.5]:.4f}s p95={q[0.95]:.4f}s "
                                 f"p99={q[0.99]:.4f}s")
                print(f"  {lbl or '(all)':40s} count={count} "
                      f"sum={_fmt_value(total)}s mean={mean:.4f}s"
                      f"{quantiles}", file=out)
            else:
                print(f"  {lbl or '(all)':40s} "
                      f"{_fmt_value(s.get('value'))}", file=out)
    events = snap.get("events") or []
    if events:
        print("\nevents:", file=out)
        for e in events[-16:]:
            extra = {k: v for k, v in e.items() if k not in ("name", "unix")}
            print(f"  {e['name']}"
                  + (f" {extra}" if extra else ""), file=out)
    traces = snap.get("recent_traces") or []
    if traces:
        print("\nrecent traces:", file=out)
        for t in traces:
            print(f"  {t['trace_id'][:8]} {t['name']} "
                  f"{t['duration_s']:.3f}s "
                  f"({sum(int(s['count']) for s in t['spans'].values())} "
                  f"spans)", file=out)


def print_tree(node: dict[str, Any], depth: int = 0, out=None) -> None:
    out = out if out is not None else sys.stdout  # call-time, like above
    pad = "  " * depth
    marker = "·" if node.get("event") else "—"
    attrs = node.get("attrs") or {}
    extra = f"  {attrs}" if attrs else ""
    print(f"{pad}{node['name']} {marker} {node.get('duration_s', 0):.4f}s"
          f"{extra}", file=out)
    for child in node.get("children", []):
        print_tree(child, depth + 1, out)


def _follow(url: str, auth: str | None = None, after: int | None = None,
            as_json: bool = False, out=sys.stdout) -> int:
    """Tail ``GET /telemetry/stream`` (SSE): print one line per event.
    Returns when the server closes the stream (shutdown) or on Ctrl-C."""
    query = f"?after={after}" if after is not None else ""
    req = urllib.request.Request(
        f"{url.rstrip('/')}/telemetry/stream{query}", headers=_headers(auth))
    try:
        resp = urllib.request.urlopen(req, timeout=60)
    except urllib.error.HTTPError as e:
        raise SystemExit(f"/telemetry/stream: {e}")
    try:
        for raw in resp:
            line = raw.decode("utf-8", "replace").rstrip("\n")
            if not line.startswith("data: "):
                continue  # id:/keepalive framing
            if as_json:
                print(line[len("data: "):], file=out, flush=True)
                continue
            try:
                record = json.loads(line[len("data: "):])
            except json.JSONDecodeError:
                continue
            extra = {k: v for k, v in record.items()
                     if k not in ("name", "unix", "seq")}
            print(f"[{record.get('seq', '?')}] {record.get('name', '?')}"
                  + (f" {extra}" if extra else ""), file=out, flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        resp.close()
    return 0


def _print_profile(target: str, data_dir: str, top: int = 20,
                   out=None) -> int:
    """``--profile <job_id|span>``: top folded stacks for a span name (or
    prefix), or per-span sample totals for a trace/job id prefix — read
    from the ``.folded``/``.traces.json`` exports under
    ``<data-dir>/logs/profiles/`` (telemetry/profiler.py)."""
    from .profiler import load_folded, load_trace_totals

    # resolved at CALL time: an ``out=sys.stdout`` default would freeze
    # whatever stdout was at first import (pytest capture objects die
    # between tests)
    out = out if out is not None else sys.stdout

    folded = load_folded(data_dir)
    if not folded:
        print(f"no profile exports under {data_dir!r} (run with "
              f"SD_PROFILE_HZ set; exports land at shutdown)",
              file=sys.stderr)
        return 1
    by_span = [(key, n) for key, n in folded.items()
               if key.split(";", 1)[0].startswith(target)]
    if by_span:
        total = sum(n for _k, n in by_span)
        print(f"{len(by_span)} stacks, {total} samples under span "
              f"'{target}*':", file=out)
        for key, n in sorted(by_span, key=lambda kv: -kv[1])[:top]:
            span, _, stack = key.partition(";")
            frames = stack.split(";")
            tail = ";".join(frames[-4:]) if len(frames) > 4 else stack
            print(f"  {n:6d}  [{span}] …{tail}", file=out)
        return 0
    traces = load_trace_totals(data_dir)
    matches = {t: spans for t, spans in traces.items()
               if t.startswith(target)}
    if matches:
        for trace_id, spans_ in sorted(matches.items()):
            total = sum(spans_.values())
            print(f"trace {trace_id}: {total} samples", file=out)
            for span, n in sorted(spans_.items(), key=lambda kv: -kv[1]):
                print(f"  {n:6d}  {span} ({n / total:.0%})", file=out)
        return 0
    known = sorted({k.split(';', 1)[0] for k in folded})
    print(f"no span or trace matching {target!r}; spans seen: "
          f"{', '.join(known)}", file=sys.stderr)
    return 1


def _print_slo(status: dict[str, Any], out=None) -> int:
    """``--slo``: render ``telemetry.sloStatus`` — one block per
    objective (SLI, budget remaining, burn per window, firing pairs)
    plus the dispatch-admission budget line."""
    out = out if out is not None else sys.stdout  # call-time, like above
    objectives = status.get("objectives") or []
    if not objectives:
        print("no SLO objectives configured", file=out)
    for o in objectives:
        scope = (f"proc={o['proc']}" if o.get("proc")
                 else f"tenant={o['tenant']}" if o.get("tenant")
                 else "all dispatches")
        firing = [p for p, f in (o.get("firing") or {}).items() if f]
        print(f"\n{o['name']} ({scope}): {o['target']:.2%} under "
              f"{o['threshold_s'] * 1000:.0f} ms over "
              f"{o['window_s'] / 3600:.1f} h", file=out)
        sli = o.get("sli")
        print(f"  sli={sli:.4%}  good={_fmt_value(o.get('good'))} "
              f"valid={_fmt_value(o.get('valid'))}  "
              f"budget_remaining={o.get('budget_remaining', 0) * 100:.1f}%",
              file=out)
        burns = o.get("burn") or {}
        if burns:
            rendered = "  ".join(f"{w}={r:g}x" for w, r in burns.items())
            print(f"  burn: {rendered}", file=out)
        print(f"  firing: {', '.join(firing) if firing else 'none'}",
              file=out)
    admission = status.get("dispatch_admission")
    if admission is not None:
        print(f"\ndispatch admission: {admission.get('in_flight', 0)}/"
              f"{admission.get('budget_inflight', 0)} in flight, "
              f"{admission.get('tenants_in_flight', 0)} tenants, "
              f"{_fmt_value(admission.get('shed', 0))} shed", file=out)
    else:
        print("\ndispatch admission: off (SD_RSPC_ADMISSION=0)", file=out)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spacedrive_tpu.telemetry",
        description="pretty-print a telemetry snapshot or job trace")
    parser.add_argument("--url", default=None,
                        help="running shell to query (default: this "
                             "process's own registry)")
    parser.add_argument("--auth", default=None, metavar="USER:PASSWORD",
                        help="basic-auth credentials for a shell started "
                             "with --auth")
    parser.add_argument("--job", default=None,
                        help="print the span tree of this job id instead "
                             "of the metrics snapshot")
    parser.add_argument("--data-dir", default=None,
                        help="with --job and no --url: read the exported "
                             "JSONL under <data-dir>/logs/traces/")
    parser.add_argument("--prometheus", action="store_true",
                        help="print the raw Prometheus text exposition")
    parser.add_argument("--json", action="store_true",
                        help="print the raw JSON instead of the table")
    parser.add_argument("--follow", action="store_true",
                        help="with --url: tail the node's live event "
                             "stream (GET /telemetry/stream, SSE) — job "
                             "transitions, fault firings, router flips, "
                             "sync sessions, alert edges, SLO burn edges "
                             "(slo.burn), admission sheds (rspc.shed), "
                             "pool resizes (pool.resize); Ctrl-C to stop")
    parser.add_argument("--slo", action="store_true",
                        help="render telemetry.sloStatus: each objective's "
                             "live SLI, error-budget remaining, multi-"
                             "window burn rates and firing pairs, plus the "
                             "dispatch-admission budget (without --url: "
                             "evaluated once against this process's own "
                             "registry)")
    parser.add_argument("--after", type=int, default=None, metavar="SEQ",
                        help="with --follow: replay ring events newer "
                             "than this sequence number first")
    parser.add_argument("--profile", default=None, metavar="JOB_OR_SPAN",
                        help="pretty-print top folded stacks for a span "
                             "name/prefix (e.g. pipeline.hash), or "
                             "per-span sample totals for a trace/job id "
                             "prefix — reads the SD_PROFILE_HZ exports "
                             "under <data-dir>/logs/profiles/")
    args = parser.parse_args(argv)

    from . import job_trace, render_prometheus, snapshot

    if args.profile:
        return _print_profile(args.profile, args.data_dir or ".")

    if args.follow:
        if not args.url:
            parser.error("--follow needs --url (it tails a RUNNING shell; "
                         "an in-process registry has no live producer)")
        return _follow(args.url, auth=args.auth, after=args.after,
                       as_json=args.json)

    if args.slo:
        if args.url:
            status = _fetch(args.url, "telemetry.sloStatus", auth=args.auth)
        else:
            # no live shell: evaluate the configured objectives once
            # against this process's own registry (useful after driving
            # work in-process, same spirit as the default snapshot)
            from .slo import SloEngine

            status = {"objectives": SloEngine().evaluate_once(),
                      "dispatch_admission": None}
        if args.json:
            print(json.dumps(status, indent=2, default=str))
            return 0
        return _print_slo(status)

    if args.job:
        if args.url:
            tree = _fetch(args.url, "telemetry.jobTrace", args.job,
                          auth=args.auth)
        else:
            tree = job_trace(args.job, data_dir=args.data_dir)
        if tree is None:
            print(f"no trace recorded for job {args.job!r}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(tree, indent=2, default=str))
        else:
            print_tree(tree)
        return 0

    if args.prometheus:
        if args.url:
            req = urllib.request.Request(
                f"{args.url.rstrip('/')}/metrics",
                headers=_headers(args.auth))
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    sys.stdout.write(resp.read().decode())
            except urllib.error.HTTPError as e:
                raise SystemExit(f"/metrics: {e}")
        else:
            sys.stdout.write(render_prometheus())
        return 0

    snap = (_fetch(args.url, "telemetry.snapshot", auth=args.auth)
            if args.url else snapshot())
    if args.json:
        print(json.dumps(snap, indent=2, default=str))
    else:
        print_snapshot(snap)
    return 0


if __name__ == "__main__":
    sys.exit(main())
