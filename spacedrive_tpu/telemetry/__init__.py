"""Unified telemetry: the metrics registry + structured spans (ISSUE 5).

One process-wide :class:`~.registry.Registry` and one bounded ring of
per-job :class:`~.spans.Trace` trees, exposed three ways:

- ``GET /metrics`` on the server shell — Prometheus text exposition;
- ``telemetry.snapshot`` / ``telemetry.jobTrace`` rspc queries;
- ``python -m spacedrive_tpu.telemetry`` — pretty-printed snapshot.

Instrumented subsystems (the metric catalogue lives in
docs/architecture/observability.md): job lifecycle (queue wait, step
latency, lane occupancy), every pipeline stage (busy/blocked/idle),
hasher dispatch (batches/files/bytes → live files-per-sec and MFU via
ops/roofline.py), utils/retry.py (attempts, backoff, budget
exhaustion), the fault seams, sync ingest, and the relay
probe/recapture path.

``SD_TELEMETRY=off`` turns every record call into a no-op (one global
read); spans still *measure* so job-report stage timings never depend
on the switch. This module imports nothing from the rest of the
package — any layer may instrument without cycles.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import deque
from pathlib import Path
from typing import Any

from . import spans as _spans
from .registry import (
    DEFAULT_BUCKETS,
    LOCK_BUCKETS,
    METRIC_NAME_RE,
    REQUEST_BUCKETS,
    Registry,
    enabled,
    reload_enabled,
    set_enabled,
)
from .spans import Span, Trace

__all__ = [
    "DEFAULT_BUCKETS", "LOCK_BUCKETS", "METRIC_NAME_RE", "REQUEST_BUCKETS",
    "Registry",
    "Span", "Trace",
    "add_event_hook", "counter", "enabled", "event", "finish_trace",
    "gauge", "histogram", "job_trace", "recent_events", "registry",
    "reload_enabled", "remove_event_hook", "render_prometheus", "reset",
    "series_values", "set_enabled", "snapshot", "span", "start_trace",
    "value",
]

_REGISTRY = Registry()

#: the flight recorder: recent events (state transitions, fault firings,
#: router flips, relay recovery, alert edges) surfaced in snapshot() and
#: streamed live through the event hooks (telemetry.watch / SSE)
_EVENTS: deque[dict[str, Any]] = deque(maxlen=256)
_EVENTS_LOCK = threading.Lock()
_EVENTS_SEQ = 0
#: fan-out hooks (the Node bridges these onto its event bus); must be
#: cheap and never raise into the instrumented hot path
_EVENT_HOOKS: list[Any] = []


def registry() -> Registry:
    return _REGISTRY


# -- metric declaration passthroughs ------------------------------------------

def counter(name: str, help_text: str = "", labels=()):
    return _REGISTRY.counter(name, help_text, labels)


def gauge(name: str, help_text: str = "", labels=()):
    return _REGISTRY.gauge(name, help_text, labels)


def histogram(name: str, help_text: str = "", labels=(),
              buckets: tuple[float, ...] = DEFAULT_BUCKETS):
    return _REGISTRY.histogram(name, help_text, labels, buckets)


def value(name: str, **label_values: str) -> float:
    return _REGISTRY.value(name, **label_values)


def series_values(name: str):
    return _REGISTRY.series_values(name)


def render_prometheus() -> str:
    return _REGISTRY.render_prometheus()


# -- spans / traces ------------------------------------------------------------

def span(trace: Trace | None, name: str, parent: Span | None = None,
         detached: bool = False, **attrs: Any) -> Span:
    """A timed section under ``trace`` — or a bare timer when there is no
    trace (telemetry off, non-job context): callers read
    ``span.duration_s`` either way. ``parent`` pins a cross-thread parent
    (pipeline stage threads nest under the job thread's run span);
    ``detached`` spans join no thread stack, so they may be entered on
    one thread and exited on another (the sharded-prefetch page span)."""
    if trace is None:
        return Span(name, trace=None, attrs=attrs)
    return trace.span(name, parent=parent, detached=detached, **attrs)


def start_trace(name: str, trace_id: str | None = None,
                resume: bool = False, **attrs: Any) -> Trace | None:
    """Open a trace (None when telemetry is off — every consumer treats a
    missing trace as 'just time, don't record'). With ``resume=True`` an
    UNFINISHED ring entry under the same id is continued instead of
    replaced — how a paused-then-resumed job keeps one tree whose span
    sums still reconcile with its accumulated report metadata (a
    cross-process resume necessarily starts fresh)."""
    if not enabled():
        return None
    if resume and trace_id is not None:
        existing = _spans.get_trace(trace_id)
        if existing is not None and not existing.finished:
            return existing
    trace = Trace(trace_id or str(uuid.uuid4()), name, attrs)
    _spans.remember(trace)
    return trace


def finish_trace(trace: Trace | None,
                 export_dir: str | Path | None = None) -> dict[str, Any] | None:
    """Close the root span, export JSONL under ``<export_dir>/logs/traces/``
    and return the summarized form (what JobReport metadata carries)."""
    if trace is None:
        return None
    trace.finish()
    summary = trace.summary()
    if export_dir is not None:
        path = _spans.export_trace(trace, export_dir)
        if path:
            summary["file"] = path
    return summary


def job_trace(job_id: str,
              data_dir: str | Path | None = None) -> dict[str, Any] | None:
    """Nested span tree for a job: the in-memory ring first, then the
    exported JSONL (survives ring eviction and restarts)."""
    trace = _spans.get_trace(job_id)
    if trace is not None:
        return trace.tree()
    if data_dir is not None:
        return _spans.load_trace_tree(job_id, data_dir)
    return None


# -- events --------------------------------------------------------------------

def event(name: str, **attrs: Any) -> None:
    """A named point-in-time occurrence (relay recovered, device verdict
    flipped, job transition, alert edge): counted, kept in the bounded
    flight-recorder ring with a process-monotonic ``seq``, and fanned out
    to the registered hooks for live streaming."""
    global _EVENTS_SEQ
    if not enabled():
        return
    # resolved per call (events are rare); the family is pre-declared
    counter("sd_telemetry_events_total", "named telemetry events",
            labels=("name",)).inc(name=name)
    with _EVENTS_LOCK:
        _EVENTS_SEQ += 1
        record = {"seq": _EVENTS_SEQ, "name": name,
                  "unix": round(time.time(), 3), **attrs}
        _EVENTS.append(record)
        hooks = list(_EVENT_HOOKS)
    for hook in hooks:
        try:
            hook(record)
        except Exception:  # a broken listener must never stall producers
            logging.getLogger(__name__).exception(
                "telemetry event hook failed for %s", name)


def add_event_hook(hook) -> None:
    """Register a live-event listener (``hook(record: dict)``); hooks run
    synchronously on the emitting thread — hand off, never block."""
    with _EVENTS_LOCK:
        if hook not in _EVENT_HOOKS:
            _EVENT_HOOKS.append(hook)


def remove_event_hook(hook) -> None:
    with _EVENTS_LOCK:
        if hook in _EVENT_HOOKS:
            _EVENT_HOOKS.remove(hook)


def recent_events(limit: int = 64,
                  after_seq: int | None = None) -> list[dict[str, Any]]:
    """Ring tail; with ``after_seq`` only events newer than that sequence
    number (how the SSE stream replays what a reconnecting tail missed)."""
    with _EVENTS_LOCK:
        events = list(_EVENTS)
    if after_seq is not None:
        events = [e for e in events if e.get("seq", 0) > after_seq]
    return events[-limit:]


# -- snapshot ------------------------------------------------------------------

def snapshot() -> dict[str, Any]:
    """The full state in one JSON-safe dict — what ``telemetry.snapshot``
    serves and what the bench's chaos pass reads."""
    return {
        "enabled": enabled(),
        "metrics": _REGISTRY.snapshot(),
        "events": recent_events(),
        "recent_traces": _spans.recent_traces(),
    }


def reset() -> None:
    """Tests: zero every series, drop traces, events and the slow-request
    ring (the declared vocabulary survives)."""
    _REGISTRY.reset()
    _spans.clear_traces()
    with _EVENTS_LOCK:
        _EVENTS.clear()
    from . import requests as _requests  # local: requests imports this module
    from . import slo as _slo  # local: slo imports this module

    _requests.clear_slow_requests()
    _slo.reset_tenant_labels()
    _declare_core()


# -- the core vocabulary -------------------------------------------------------
# Declared eagerly so a scrape exposes the full metric set from process
# start, not only after the first scan/retry/fault touches each family.
# Instrumentation sites re-declare their families (same name/labels) to
# get module-local handles — the registry memoizes by name, and a
# mismatched re-declaration raises at the site module's import, which is
# the intended fail-fast: vocabulary drift breaks loudly in any test run
# instead of silently forking the series.

def _declare_core() -> None:
    gauge("sd_scan_files_per_sec",
          "files/s of the most recent completed identify pass")
    gauge("sd_hash_mfu",
          "u32-VPU model-op-utilization of the last hash batch "
          "(ops/roofline.py model)")
    gauge("sd_hash_files_per_sec", "files/s of the last hash batch")
    gauge("sd_hash_bytes_per_sec", "payload bytes/s of the last hash batch")
    busy = counter("sd_pipeline_stage_busy_seconds",
                   "time each pipeline stage spent executing its callable",
                   labels=("stage",))
    blocked = counter("sd_pipeline_stage_blocked_seconds",
                      "time each stage spent blocked on a full downstream "
                      "queue (backpressure)", labels=("stage",))
    idle = counter("sd_pipeline_stage_idle_seconds",
                   "time each stage spent waiting on an empty upstream "
                   "queue", labels=("stage",))
    for fam in (busy, blocked, idle):
        for stage in ("page", "hash", "commit"):
            fam.labels(stage=stage)
    counter("sd_retry_attempts_total",
            "re-calls made after a transient failure (utils/retry.py)")
    counter("sd_retry_backoff_seconds_total",
            "total wall time spent in retry backoff")
    counter("sd_retry_gave_up_total",
            "retry budgets exhausted (attempts or wall budget)")
    counter("sd_faults_fired_total", "injected faults fired, per seam:kind",
            labels=("seam", "kind"))
    counter("sd_recovered_batches_total",
            "hash batches re-dispatched on the CPU ladder after a device "
            "failure")
    counter("sd_quarantined_files_total",
            "per-item failures quarantined by the identifier")
    counter("sd_relay_probe_total", "relay liveness probes by outcome",
            labels=("outcome",))
    counter("sd_relay_recovered_total",
            "relay recoveries observed by the recapture watcher")
    # sync ingest families carry a bounded-cardinality ``peer`` label
    # (hash-truncated node id, "local" for transport-less ingest) so two
    # aggressive peers are distinguishable in one scrape
    counter("sd_sync_ops_ingested_total", "CRDT ops received for ingest",
            labels=("peer",))
    counter("sd_sync_ops_applied_total",
            "ingested CRDT ops with materialized effect", labels=("peer",))
    counter("sd_p2p_hash_requests_total", "outbound remote-hasher batches")
    counter("sd_p2p_hash_bytes_total",
            "cas-message bytes shipped to remote hashers")
    histogram("sd_sync_window_seconds", "latency of one ingest window",
              labels=("peer",))
    # mesh observability (ISSUE 7): per-peer convergence lag + remote
    # attribution; declared here so the catalogue is scrape-visible from
    # boot (telemetry/mesh.py holds the matching module handles)
    gauge("sd_sync_peer_lag_ops",
          "CRDT ops the peer has logged that this node has not yet "
          "ingested (sender-declared backlog after each sync window)",
          labels=("peer",))
    gauge("sd_sync_peer_lag_seconds",
          "HLC delta between the peer's watermark and the newest op "
          "applied from it", labels=("peer",))
    histogram("sd_sync_apply_delay_seconds",
              "op_created -> op_applied end-to-end latency (op HLC stamp "
              "vs local wall clock at ingest)", labels=("peer",))
    counter("sd_sync_remote_windows_total",
            "sync ingest windows received per peer", labels=("peer",))
    counter("sd_sync_remote_sessions_total",
            "sync-over-wire sessions completed per peer", labels=("peer",))
    counter("sd_p2p_hash_serve_total",
            "inbound remote-hasher batches served per peer",
            labels=("peer",))
    counter("sd_p2p_hash_serve_bytes_total",
            "cas-message bytes hashed on behalf of remote peers",
            labels=("peer",))
    gauge("sd_alerts_firing",
          "1 while the named alert rule is firing (telemetry/alerts.py)",
          labels=("rule",))
    histogram("sd_job_queue_wait_seconds",
              "dispatch-queue wait per job", labels=("lane",))
    histogram("sd_job_step_seconds", "sequential step latency per job",
              labels=("job",))
    gauge("sd_jobs_running", "running workers per lane", labels=("lane",))
    gauge("sd_jobs_queued", "jobs waiting for lane capacity")
    counter("sd_jobs_completed_total", "finished jobs by name and status",
            labels=("job", "status"))
    counter("sd_commit_txns_total",
            "durable transactions opened by the pipeline committer (group "
            "commit coalesces SD_COMMIT_GROUP pages into each)")
    counter("sd_commit_txn_pages_total",
            "pipeline pages made durable through group-commit transactions")
    gauge("sd_hash_router_bytes_per_sec",
          "EWMA transfer-inclusive payload bytes/s per engine (router "
          "input)", labels=("backend",))
    gauge("sd_hash_router_device_mfu",
          "u32-VPU MFU implied by the router's device-engine EWMA rate")
    counter("sd_hash_router_flips_total",
            "engine flips by the per-batch hash router (hysteresis-damped)")
    counter("sd_hash_router_batches_total",
            "hash (sub-)batches the hybrid router dispatched per engine",
            labels=("backend",))
    counter("sd_hash_batches_total", "hash batches dispatched per backend",
            labels=("backend",))
    counter("sd_hash_files_total", "files hashed per backend",
            labels=("backend",))
    counter("sd_hash_bytes_total", "cas-message payload bytes hashed per "
            "backend", labels=("backend",))
    histogram("sd_hash_batch_seconds", "hash batch latency per backend",
              labels=("backend",))
    counter("sd_telemetry_events_total", "named telemetry events",
            labels=("name",))
    # crash-consistent durability (ISSUE 9): boot integrity + repair ladder
    # + disk-full degradation + accept-layer throttling (recovery.py,
    # p2p/throttle.py hold the matching module handles)
    boot = counter("sd_boot_integrity_checks_total",
                   "boot-time library DB integrity checks by outcome",
                   labels=("outcome",))
    for outcome in ("ok", "corrupt"):
        boot.labels(outcome=outcome)
    counter("sd_boot_integrity_wal_recovered_total",
            "boots that found (and replayed) a non-empty WAL sidecar")
    histogram("sd_boot_integrity_check_seconds",
              "latency of one boot-time quick_check pass")
    counter("sd_recovery_repairs_total",
            "repair-ladder actions taken on a corrupt library DB",
            labels=("action",))
    counter("sd_recovery_cold_resumed_jobs_total",
            "interrupted jobs revived from their checkpoints at boot")
    counter("sd_recovery_disk_full_total",
            "ENOSPC hits absorbed by graceful degradation, per site",
            labels=("site",))
    counter("sd_p2p_throttled_sessions_total",
            "inbound sessions refused by the per-peer accept-layer token "
            "bucket", labels=("peer",))
    # WAN survival (ISSUE 13): the link-level network fault model
    # (faults/net.py) + accept-layer auto-ban (p2p/throttle.py hold the
    # matching module handles)
    net_msgs = counter(
        "sd_net_link_messages_total",
        "messages that crossed the modeled network, by verdict "
        "(ok | drop | cut)", labels=("verdict",))
    for verdict in ("ok", "drop", "cut"):
        net_msgs.labels(verdict=verdict)
    counter("sd_net_link_bytes_total",
            "payload bytes delivered across the modeled network")
    counter("sd_net_link_delay_seconds_total",
            "injected link delay (latency + jitter + serialization)")
    gauge("sd_net_link_partitions_active",
          "partition windows currently cutting at least one link")
    gauge("sd_p2p_banned_peers",
          "peers currently serving an accept-layer ban")
    counter("sd_p2p_bans_total",
            "accept-layer bans imposed, by triggering reason",
            labels=("reason",))
    # serving-tier observability (ISSUE 10): per-procedure request
    # telemetry, HTTP-layer families, the span-tagged sampling profiler
    # and the process resource watcher (telemetry/requests.py,
    # telemetry/profiler.py, server/shell.py, models/base.py hold the
    # matching module handles)
    counter("sd_rspc_requests_total",
            "rspc procedure dispatches by procedure, kind and outcome",
            labels=("proc", "kind", "outcome"))
    histogram("sd_rspc_request_seconds",
              "rspc dispatch latency per procedure", labels=("proc",),
              buckets=REQUEST_BUCKETS)
    gauge("sd_rspc_in_flight", "rspc dispatches currently executing")
    counter("sd_rspc_payload_bytes_total",
            "transport payload bytes per procedure and direction (in = "
            "request body, out = serialized response)",
            labels=("proc", "direction"))
    counter("sd_rspc_slow_requests_total",
            "requests slower than SD_SLOW_REQUEST_MS (each keeps its span "
            "tree in the slow-request ring)", labels=("proc",))
    gauge("sd_rspc_request_p99_seconds",
          "estimated p99 of sd_rspc_request_seconds per procedure "
          "(published by the resource-watcher tick; alert target — "
          "histograms are not rule targets)", labels=("proc",))
    # serve-tier SLO engine (ISSUE 20): bounded-cardinality per-tenant
    # request families (tenant = 8-hex library-id hash, LRU-capped with an
    # `other` overflow — telemetry/slo.py tenant_label), the per-objective
    # SLO gauges the engine publishes, and the rspc dispatch-admission
    # families (sync/admission.py DispatchBudget holds those handles)
    counter("sd_rspc_tenant_requests_total",
            "rspc dispatches per tenant class and outcome (tenant = "
            "bounded library-id hash; shed = admission-control BUSY, "
            "excluded from SLO error ratios)",
            labels=("tenant", "outcome"))
    histogram("sd_rspc_tenant_request_seconds",
              "rspc dispatch latency per tenant class",
              labels=("tenant",), buckets=REQUEST_BUCKETS)
    gauge("sd_slo_budget_remaining",
          "error budget remaining per SLO objective over its budget "
          "window (1 = untouched, 0 = exhausted)", labels=("objective",))
    gauge("sd_slo_burn_rate",
          "error-budget burn rate per SLO objective and trailing window "
          "(1 = burning exactly the sustainable rate)",
          labels=("objective", "window"))
    counter("sd_rspc_shed_total",
            "rspc dispatches answered BUSY by admission control, per "
            "tenant class", labels=("tenant",))
    gauge("sd_rspc_admission_in_flight",
          "rspc dispatches currently admitted by the dispatch budget")
    gauge("sd_rspc_admission_budget",
          "configured max concurrent rspc dispatches (SD_RSPC_BUDGET)")
    counter("sd_http_requests_total",
            "HTTP requests served by the shell, by route class and status",
            labels=("route", "status"))
    histogram("sd_http_request_seconds",
              "HTTP request latency per route class", labels=("route",),
              buckets=REQUEST_BUCKETS)
    counter("sd_http_response_bytes_total",
            "response payload bytes per route class (file/range streams "
            "count the streamed window)", labels=("route",))
    counter("sd_http_ws_messages_total",
            "websocket text messages by direction (in = client frames, "
            "out = responses/subscription events)", labels=("direction",))
    counter("sd_profile_samples_total",
            "wall-clock profiler samples attributed per active span name "
            "('other' = the sampled thread had no open span)",
            labels=("span",))
    gauge("sd_proc_rss_bytes", "resident set size of this process")
    gauge("sd_proc_open_fds", "open file descriptors of this process")
    gauge("sd_proc_threads", "live Python threads in this process")
    histogram("sd_db_reader_wait_seconds",
              "time reads spent waiting for the WAL reader connection "
              "lock (contended acquisitions only — reader/writer "
              "contention under serving load)")
    # multi-process reader pool (ISSUE 11): per-worker serving telemetry,
    # recorded in the NODE process at the dispatch seam (worker children
    # run with telemetry disabled; their stats ride the reply pipe) —
    # server/pool.py holds the matching module handles
    counter("sd_serve_worker_requests_total",
            "pool-dispatched query requests per worker slot and outcome "
            "(failover = the request was re-run in-process)",
            labels=("worker", "outcome"))
    histogram("sd_serve_worker_request_seconds",
              "round-trip latency of pool-dispatched queries per worker "
              "slot", labels=("worker",), buckets=REQUEST_BUCKETS)
    counter("sd_serve_worker_cache_total",
            "worker hot-directory-page LRU lookups by result (hit entries "
            "are watermark-checked — a stale page can never hit)",
            labels=("worker", "result"))
    counter("sd_serve_worker_restarts_total",
            "worker respawns by reason (crash = process died, timeout = "
            "unresponsive past SD_SERVE_REQUEST_TIMEOUT_S, health = "
            "failed ping)", labels=("worker", "reason"))
    gauge("sd_serve_workers", "live reader-pool worker processes")
    counter("sd_serve_invalidations_total",
            "per-library watermark bumps pushed to the worker page caches")
    histogram("sd_serve_queue_wait_seconds",
              "time a pool dispatch waited for an idle worker (saturation "
              "spills record the full SD_SERVE_QUEUE_WAIT_S wait) — the "
              "autosizer's input signal", buckets=REQUEST_BUCKETS)
    counter("sd_serve_pool_resizes_total",
            "autosizer resize decisions by direction (grow | shrink), "
            "each also a pool.resize flight-recorder event",
            labels=("direction",))
    # distributed read replicas (ISSUE 19): the ReplicaRouter dispatch
    # seam plus the replica-side serve arm — server/replica.py holds the
    # matching module handles. ``peer`` labels are mesh.peer_label hashes
    # (8 hex chars, bounded by fleet size).
    counter("sd_replica_dispatches_total",
            "pool-marked queries dispatched to a remote replica, per peer "
            "and outcome (ok | not_eligible | busy | error)",
            labels=("peer", "outcome"))
    counter("sd_replica_eligibility_rejections_total",
            "replica dispatches answered NOT_ELIGIBLE because the peer's "
            "applied HLC watermark did not cover the client's last write "
            "(the never-serve-a-stale-row gate)", labels=("peer",))
    counter("sd_replica_failovers_total",
            "replica-tier degradations to the next ladder rung (reason: "
            "not_eligible | busy | error | no_peers)", labels=("reason",))
    histogram("sd_replica_request_seconds",
              "round-trip latency of replica-served queries per peer",
              labels=("peer",), buckets=REQUEST_BUCKETS)
    counter("sd_replica_serves_total",
            "replica-SIDE serve outcomes for remote H_QUERY dispatches "
            "(ok | not_eligible | busy | error)", labels=("outcome",))
    # device-resident query engine (ISSUE 15): columnar search index +
    # per-query backend router + refresh machinery (search/engine.py
    # holds the matching module handles). ``library`` labels are the
    # 8-hex library-id prefix — bounded like the sync ``peer`` labels.
    gauge("sd_search_index_rows",
          "live FilePath rows in the columnar search index per library",
          labels=("library",))
    gauge("sd_search_index_bytes",
          "resident bytes of the columnar search index per library",
          labels=("library",))
    histogram("sd_search_refresh_seconds",
              "latency of one search-index refresh pass (full or "
              "incremental)")
    counter("sd_search_refresh_total",
            "search-index refreshes by kind (full = rebuild, incremental "
            "= journal-driven delta)", labels=("kind",))
    gauge("sd_search_refresh_lag",
          "watermark bumps the search index is behind the library "
          "(0 = fresh; queries fall back to SQLite while > 0)",
          labels=("library",))
    counter("sd_search_queries_total",
            "search queries served per backend (device = JAX/Pallas "
            "kernels, cpu = numpy columnar, sqlite = the oracle path "
            "while the engine is armed)", labels=("backend",))
    histogram("sd_search_query_seconds",
              "search predicate-scoring latency per backend",
              labels=("backend",))
    counter("sd_search_fallbacks_total",
            "engine-armed queries that fell back to SQLite, by reason "
            "(stale | tags | needle | arg | toolarge | error | "
            "ineligible)", labels=("reason",))
    counter("sd_search_router_flips_total",
            "engine flips by the per-query search backend router "
            "(hysteresis-damped, the PR 6 BackendRouter)")
    counter("sd_search_router_batches_total",
            "scoring dispatches the search router measured per backend",
            labels=("backend",))
    gauge("sd_search_router_bytes_per_sec",
          "EWMA scan bytes/s per search backend (router input)",
          labels=("backend",))
    # concurrency sanitizer (ISSUE 14): named-lock contention telemetry,
    # recorded only on SD_LOCK_SANITIZER=1 runs (disabled, SdLock returns
    # the bare threading primitive). ONE definition: utils/locks.py owns
    # the declarations and records through the same memoized handles —
    # calling it here makes the vocabulary scrape-visible from boot.
    from ..utils.locks import declare_metrics as _declare_lock_metrics

    _declare_lock_metrics()


_declare_core()
