"""Mesh observability: cross-node trace propagation + convergence lag.

Until ISSUE 7 a sync window went dark the moment it left the sender: the
receiver's ingest work was unattributed (no per-peer series) and causally
disconnected (its spans lived in a fresh local trace). This module is the
Dapper-shaped answer — a compact **trace-context envelope** rides inside
sync windows and p2p hash-batch requests, so the receiver's spans parent
under the *sender's* span ids and the JSONL exports of both nodes stitch
into one tree by ``trace_id``:

- :class:`TraceContext` — ``(trace_id, parent span_id, origin node id,
  origin HLC watermark, pending op backlog)``, wire form a 5-key dict;
- per-node **span-id bases** (a 24-bit hash of the node id shifted above
  the local counter) keep ids collision-free when two processes append to
  one logical trace;
- **convergence lag**: every ingest window updates per-peer gauges —
  ``sd_sync_peer_lag_ops`` (the sender-declared backlog left after the
  window) and ``sd_sync_peer_lag_seconds`` (sender HLC watermark minus
  the newest timestamp we applied) — plus an end-to-end
  ``sd_sync_apply_delay_seconds`` histogram (op_created→op_applied from
  the op's HLC stamp). These are the fleet-soak gate's convergence
  metric: both lag series return to 0 when a peer pair is in sync.

Like the rest of ``spacedrive_tpu.telemetry``, this module imports
nothing from the rest of the package (any layer may instrument without
cycles); the NTP64→unix conversion is inlined rather than imported from
``sync/hlc.py`` for that reason.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any

from . import counter, gauge, histogram, enabled
from . import spans as _spans
from .spans import Span, Trace

__all__ = [
    "TraceContext", "apply_delay_series", "continue_trace", "new_trace",
    "outbound_context", "peer_label", "record_ingest_window", "remote_span",
    "span_id_base",
]

#: sender-declared backlog after each ingest window, per peer
_PEER_LAG_OPS = gauge(
    "sd_sync_peer_lag_ops",
    "CRDT ops the peer has logged that this node has not yet ingested "
    "(sender-declared backlog after each sync window)", labels=("peer",))
_PEER_LAG_SECONDS = gauge(
    "sd_sync_peer_lag_seconds",
    "HLC delta between the peer's watermark and the newest op applied "
    "from it", labels=("peer",))
_APPLY_DELAY = histogram(
    "sd_sync_apply_delay_seconds",
    "op_created -> op_applied end-to-end latency (op HLC stamp vs local "
    "wall clock at ingest)", labels=("peer",))
_REMOTE_WINDOWS = counter(
    "sd_sync_remote_windows_total",
    "sync ingest windows received per peer", labels=("peer",))
_REMOTE_SESSIONS = counter(
    "sd_sync_remote_sessions_total",
    "sync-over-wire sessions completed per peer", labels=("peer",))
_HASH_SERVE = counter(
    "sd_p2p_hash_serve_total",
    "inbound remote-hasher batches served per peer", labels=("peer",))
_HASH_SERVE_BYTES = counter(
    "sd_p2p_hash_serve_bytes_total",
    "cas-message bytes hashed on behalf of remote peers", labels=("peer",))
# admission control (ISSUE 8): BUSY answers on the p2p receive path —
# sent when OUR budget sheds a peer's work, received when a peer sheds ours
_BUSY_SENT = counter(
    "sd_p2p_busy_replies_total",
    "BUSY answers this node sent (its admission budget shed the request)",
    labels=("peer",))
_BUSY_RECEIVED = counter(
    "sd_p2p_busy_received_total",
    "BUSY answers received from peers (their budget shed our request)",
    labels=("peer",))
_BUSY_BACKOFF_S = counter(
    "sd_p2p_busy_backoff_seconds_total",
    "wall time spent backing off after a peer's BUSY answer")


def peer_label(identity: str | None) -> str:
    """Bounded-cardinality peer label: an 8-hex-char hash of the node's
    identity (never the raw identity — scrape labels must stay short and
    a fleet of peers must not explode series cardinality beyond the
    peer count itself). ``local`` for in-process/transport-less ingest."""
    if not identity:
        return "local"
    return hashlib.blake2s(identity.encode("utf-8", "replace"),
                           digest_size=4).hexdigest()


def span_id_base(origin: str | None) -> int:
    """Per-node span-id base: 24 bits of the node id above bit 32. Two
    nodes appending to one stitched trace allocate from disjoint ranges,
    so a merged JSONL can never collide on span ids."""
    if not origin:
        return 0
    h = hashlib.blake2s(origin.encode("utf-8", "replace"), digest_size=3)
    return int.from_bytes(h.digest(), "big") << 32


def _ntp64_to_unix(ts: int) -> float:
    # sync/hlc.py's NTP64 layout: high 32 bits unix seconds, low 32 fraction
    return (ts >> 32) + (ts & 0xFFFFFFFF) / (1 << 32)


@dataclass(frozen=True)
class TraceContext:
    """The compact envelope a cross-node exchange carries."""

    trace_id: str
    span_id: int          #: the sender-side span the receiver parents under
    origin: str = ""      #: sender node id (attribution/debug, not auth)
    hlc: int = 0          #: sender's HLC watermark when the frame was built
    pending: int | None = None  #: sender-declared ops left AFTER this window

    def to_wire(self) -> dict[str, Any]:
        wire: dict[str, Any] = {"t": self.trace_id, "s": self.span_id,
                                "o": self.origin, "h": self.hlc}
        if self.pending is not None:
            wire["p"] = self.pending
        return wire

    @classmethod
    def from_wire(cls, wire: Any) -> "TraceContext | None":
        """Defensive decode: a malformed envelope from a buggy/malicious
        peer degrades to 'no context', never to an exception — and the
        trace_id is validated against the filename-safe pattern because
        it eventually reaches the traces directory on disk."""
        if not isinstance(wire, dict):
            return None
        trace_id, span_id = wire.get("t"), wire.get("s")
        if not isinstance(trace_id, str) or len(trace_id) > 128 \
                or not _spans._TRACE_ID_RE.match(trace_id):
            return None
        if not isinstance(span_id, int) or isinstance(span_id, bool) \
                or span_id < 0:
            return None
        origin = wire.get("o")
        hlc = wire.get("h")
        pending = wire.get("p")
        return cls(
            trace_id=trace_id, span_id=span_id,
            origin=origin if isinstance(origin, str) else "",
            hlc=hlc if isinstance(hlc, int)
            and not isinstance(hlc, bool) and hlc >= 0 else 0,
            pending=pending if isinstance(pending, int)
            and not isinstance(pending, bool) and pending >= 0 else None)


# -- trace plumbing ------------------------------------------------------------

def new_trace(name: str, origin: str, trace_id: str,
              **attrs: Any) -> Trace | None:
    """Open a mesh trace on the SENDING side (span ids based off this
    node's id); remembered in the process ring like job traces so
    ``telemetry.jobTrace`` serves it by trace_id."""
    if not enabled():
        return None
    trace = Trace(trace_id, name, {**attrs, "origin": origin},
                  span_id_base=span_id_base(origin))
    _spans.remember(trace)
    return trace


def continue_trace(ctx: TraceContext | None, origin: str,
                   name: str = "sync.mesh") -> Trace | None:
    """The RECEIVING side of propagation: append to the trace named by the
    envelope. In-process (same ring) that is the sender's own Trace
    object; cross-process it is a fresh Trace under the same trace_id
    whose span ids come from THIS node's base — the two JSONL exports
    stitch by trace_id."""
    if ctx is None or not enabled():
        return None
    existing = _spans.get_trace(ctx.trace_id)
    if existing is not None and not existing.finished:
        return existing
    trace = Trace(ctx.trace_id, name,
                  {"origin": ctx.origin, "continued_on": origin},
                  span_id_base=span_id_base(origin))
    _spans.remember(trace)
    return trace


def remote_span(trace: Trace | None, ctx: TraceContext | None,
                name: str, **attrs: Any) -> Span:
    """A span parented under the REMOTE span named by the envelope (or a
    bare timer when recording is off)."""
    if trace is None:
        return Span(name, trace=None, attrs=attrs)
    return trace.span(name, parent_id=ctx.span_id if ctx else None, **attrs)


def outbound_context(origin: str = "", hlc: int = 0,
                     pending: int | None = None) -> TraceContext | None:
    """Envelope for an outbound exchange made from inside a span (the
    remote-hasher path): names the calling thread's innermost open span
    so the serving peer's spans stitch under the caller's job trace."""
    if not enabled():
        return None
    trace = _spans.current_trace()
    if trace is None:
        return None
    return TraceContext(trace.trace_id, trace.current_span_id(),
                        origin=origin, hlc=hlc, pending=pending)


#: retention for session-scoped mesh exports: unlike job traces (one file
#: per job id, overwritten on re-run), every sync session writes a fresh
#: uuid-suffixed ``sync-*.jsonl`` — without a cap a chatty long-lived
#: node would grow logs/traces/ unboundedly
MAX_SESSION_TRACE_FILES = 256


def prune_session_traces(base_dir,
                         keep: int = MAX_SESSION_TRACE_FILES) -> None:
    """Drop the oldest session-trace exports beyond ``keep`` (best-effort;
    called after every session export on both the sending and receiving
    side)."""
    try:
        files = sorted(_spans.traces_dir(base_dir).glob("sync-*.jsonl"),
                       key=lambda p: p.stat().st_mtime)
        for stale in files[:-keep] if keep > 0 else files:
            stale.unlink(missing_ok=True)
    except OSError:
        pass


def export_partial(trace: Trace | None, base_dir) -> str | None:
    """Export a mesh trace's records WITHOUT finishing it: no local root
    record is added, so a stitched merge keeps exactly one root — the
    originating node's."""
    if trace is None:
        return None
    path = _spans.export_trace(trace, base_dir)
    prune_session_traces(base_dir)
    return path


# -- convergence lag -----------------------------------------------------------

def record_ingest_window(label: str, ctx: TraceContext | None,
                         max_applied_ts: int) -> None:
    """Update the per-peer lag gauges from one ingest window's envelope.
    ``max_applied_ts`` is the newest HLC timestamp the window carried
    (0 for an empty window)."""
    if not enabled():
        return
    _REMOTE_WINDOWS.inc(peer=label)
    if ctx is None:
        return
    if ctx.pending is not None:
        _PEER_LAG_OPS.set(float(max(0, ctx.pending)), peer=label)
    if ctx.hlc:
        if max_applied_ts:
            _PEER_LAG_SECONDS.set(
                max(0.0, _ntp64_to_unix(ctx.hlc)
                    - _ntp64_to_unix(max_applied_ts)), peer=label)
        elif not ctx.pending:
            # empty final window: nothing newer exists on the peer
            _PEER_LAG_SECONDS.set(0.0, peer=label)


def record_session(label: str) -> None:
    _REMOTE_SESSIONS.inc(peer=label)


def record_hash_serve(label: str, payload_bytes: int) -> None:
    _HASH_SERVE.inc(peer=label)
    _HASH_SERVE_BYTES.inc(payload_bytes, peer=label)


def record_busy_sent(label: str) -> None:
    _BUSY_SENT.inc(peer=label)


def record_busy_received(label: str) -> None:
    _BUSY_RECEIVED.inc(peer=label)


def record_busy_backoff(backoff_s: float) -> None:
    """Wall time ACTUALLY about to be spent sleeping on a peer's BUSY —
    callers record this adjacent to the sleep, after any give-up checks,
    so the counter never claims backoff that was skipped."""
    if backoff_s > 0:
        _BUSY_BACKOFF_S.inc(backoff_s)


def apply_delay_series(label: str):
    """Memoizable per-peer histogram series handle for the per-op
    op_created→op_applied delay (callers hoist this out of the loop)."""
    return _APPLY_DELAY.labels(peer=label)
