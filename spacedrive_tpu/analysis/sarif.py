"""SARIF 2.1.0 export for sdlint findings.

SARIF (Static Analysis Results Interchange Format, OASIS standard) is
what code-review tooling ingests — GitHub code scanning, VS Code SARIF
viewers, reviewdog. ``python -m spacedrive_tpu.analysis --sarif`` emits
one run with every registered pass as a ``reportingDescriptor`` rule
and every finding as a ``result``; findings the baseline ratchet
tolerates carry a ``suppressions`` entry (kind ``external``,
justification ``baseline``) so viewers show them greyed-out instead of
hiding the debt entirely.

Only the stable core of the spec is emitted — tool metadata, rules,
results with physical locations, suppressions — because the consumers
above need nothing more and every extra property is another thing the
round-trip test must pin.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

from .engine import AnalysisPass, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _rule(ap: AnalysisPass) -> dict:
    return {
        "id": ap.id,
        "shortDescription": {"text": ap.description or ap.id},
    }


def _result(f: Finding, baselined: bool) -> dict:
    result = {
        "ruleId": f.pass_id,
        "level": "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.relpath},
                # findings from a missing file (stale ledger rows) have
                # lineno 0; SARIF regions are 1-based so clamp up
                "region": {"startLine": max(1, f.lineno)},
            },
        }],
    }
    if baselined:
        result["suppressions"] = [{
            "kind": "external",
            "justification": "baseline",
        }]
    return result


def to_sarif(findings: Iterable[Finding], new: Iterable[Finding],
             passes: Iterable[AnalysisPass], root: Path) -> dict:
    """Findings → a SARIF 2.1.0 log dict (one run). ``new`` is the
    subset beyond the baseline; everything else is marked suppressed.
    Membership is by identity — the ratchet hands back the same Finding
    objects it was given, and two findings with equal fields at
    different sites must not alias each other's suppression state."""
    new_ids = {id(f) for f in new}
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "sdlint",
                    "informationUri":
                        "docs/static-analysis.md",
                    "rules": [_rule(ap) for ap in passes],
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"uri": root.resolve().as_uri() + "/"},
            },
            "results": [_result(f, id(f) not in new_ids)
                        for f in findings],
        }],
    }
