"""replica-purity: replica-eligible rspc handlers must not read
node-local divergent state.

The distributed serve tier (ISSUE 19, server/replica.py) dispatches
``pool=True`` query handlers to watermark-eligible REMOTE peers. Watermark
eligibility proves the peer's *synced library state* covers the client's
last write — it proves nothing about state that never syncs. A handler
that reads node-local mutable state (the volume table, live job rows, the
node's own data_dir disk stats) would pass worker-purity, serve fine from
the local pool, and then quietly answer with the REPLICA's volumes/jobs/
free-space when dispatched over the mesh — a wrong answer no watermark
check can catch. This pass makes "replica-safe" a static contract on top
of worker-purity:

- inside any replica-eligible handler (``pool=True`` without
  ``replica=False``), ``node.data_dir`` access is a finding — the path
  and the disk behind it are per-node (worker-purity allows it because
  pool workers share the node's machine; replicas don't);
- ``db.find/find_one/count(Model, ...)`` over a divergent model
  (:data:`DIVERGENT_MODELS` — tables with no sync spec whose rows are
  node-owned: volume, job, node, instance, statistics, notification) is
  a finding;
- raw SQL string literals selecting FROM/JOINing those tables are
  findings too.

Handlers whose answer is *legitimately* node-specific opt out with
``replica=False`` (libraries.statistics does) — they keep the local pool
and drop off the replica tier, and this pass skips them. Scoped to
``api/`` like worker-purity; module-local.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name
from .query_discipline import _is_db_receiver
from .worker_purity import _pool_decorator

#: models whose tables carry no sync spec and whose rows are node-owned —
#: converged peers still disagree on them (models/schema.py: SYNC = None
#: or absent)
DIVERGENT_MODELS = frozenset({
    "Volume", "JobRow", "NodeRow", "Instance", "Statistics", "Notification",
})
#: the same set at the SQL layer
DIVERGENT_TABLES = ("volume", "job", "node", "instance", "statistics",
                    "notification")
_SQL_DIVERGENT = re.compile(
    r"\b(?:from|join)\s+(" + "|".join(DIVERGENT_TABLES) + r")\b",
    re.IGNORECASE)
#: db read entry points (write surfaces are query-discipline's problem)
READ_ATTRS = frozenset({"find", "find_one", "count"})


def _replica_eligible(node: ast.FunctionDef | ast.AsyncFunctionDef
                      ) -> tuple[str, bool] | None:
    """(decorator name, library-scoped) when this handler rides the
    replica tier: pool-marked AND not opted out with ``replica=False``."""
    marked = _pool_decorator(node)
    if marked is None:
        return None
    for dec in node.decorator_list:
        if isinstance(dec, ast.Call) and any(
                kw.arg == "replica" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in dec.keywords):
            return None
    return marked


class ReplicaPurityPass(AnalysisPass):
    id = "replica-purity"
    description = ("replica-eligible query handlers reading node-local "
                   "divergent state (volumes, jobs, data_dir) — a "
                   "watermark-eligible peer would still answer with ITS "
                   "OWN rows; opt out with replica=False")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs("api"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            marked = _replica_eligible(node)
            if marked is None:
                continue
            decorator, _library_scoped = marked
            params = [a.arg for a in node.args.args]
            node_param = params[0] if params else None
            for inner in ast.walk(node):
                if isinstance(inner, ast.Attribute) \
                        and isinstance(inner.value, ast.Name) \
                        and inner.value.id == node_param \
                        and inner.attr == "data_dir":
                    yield ctx.finding(
                        inner.lineno, self.id,
                        f"'{inner.value.id}.data_dir' in replica-eligible "
                        f"{decorator} handler '{node.name}' — the data dir "
                        f"is per-node; a remote replica would answer from "
                        f"its own disk (mark replica=False if the answer "
                        f"is meant to be node-specific)")
                if isinstance(inner, ast.Call) \
                        and isinstance(inner.func, ast.Attribute) \
                        and inner.func.attr in READ_ATTRS:
                    chain = dotted_name(inner.func)
                    if chain is None or not _is_db_receiver(chain):
                        continue
                    model = inner.args[0] if inner.args else None
                    if isinstance(model, ast.Name) \
                            and model.id in DIVERGENT_MODELS:
                        yield ctx.finding(
                            inner.lineno, self.id,
                            f"'{chain}({model.id}, ...)' in replica-"
                            f"eligible {decorator} handler '{node.name}' — "
                            f"table '{model.id}' has no sync spec, so "
                            f"peers diverge on it even when watermark-"
                            f"eligible (mark replica=False)")
                if isinstance(inner, ast.Constant) \
                        and isinstance(inner.value, str):
                    m = _SQL_DIVERGENT.search(inner.value)
                    if m:
                        yield ctx.finding(
                            inner.lineno, self.id,
                            f"SQL over node-local table '{m.group(1)}' in "
                            f"replica-eligible {decorator} handler "
                            f"'{node.name}' — unsynced rows diverge "
                            f"across peers (mark replica=False)")
