"""hold-blocking: no blocking call reachable while a named lock is held.

The interprocedural upgrade of the lockset family. The per-class
``lockset`` pass proves mutations happen *under* a lock; this pass
proves nothing SLOW happens under one: ``time.sleep``, socket and
subprocess calls, ``db.query``/``db.transaction``-class DB work, and
file I/O must not be reachable — at any call depth, across modules —
from inside a ``with <named-lock>:`` body. Blocking under a contended
lock is the canonical serve-tail killer: every waiter inherits the
holder's I/O latency, and under ``SD_LOCK_SANITIZER=1`` the soak only
catches the shape when the slow path actually fires. This pass catches
it at parse time with a transitive witness path in the finding.

What counts as a held lock: a ``with self.X:`` item where ``X`` is a
lock attribute of the enclosing class (``Lock``/``SdLock``/``RLock``/
``SdRLock``/``Condition``, asyncio locks excluded — they guard await
interleave, not threads), or a ``with NAME:`` over a module-level lock.
Bare ``.acquire()`` pairs stay the per-file ``lock-discipline`` pass's
domain. ``async with`` never holds a thread lock here.

Scoping: ``models/`` holders are exempt by design — ``db.writer`` /
``db.reader`` exist precisely to serialize SQLite I/O, so "DB call
under the DB lock" is the intended shape there, not a defect. The
witness path renders function names only (never line numbers): the
message is part of the baseline key and must survive unrelated edits.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..callgraph import (LOCK_FACTORIES, FunctionInfo, ModuleInfo,
                         blocking_call_reason, walk_own_body, witness)
from ..engine import Finding, ProjectContext, ProjectPass, dotted_name


def _classify(call: ast.Call, mi: ModuleInfo) -> str | None:
    # under a lock even bare open() is a finding: the page cache does
    # not bound first-touch latency and the lock serializes every waiter
    return blocking_call_reason(call, mi, include_db=True,
                                include_open=True)


def _module_locks(mi: ModuleInfo) -> set[str]:
    """Module-level ``NAME = Lock()/SdLock(...)`` bindings."""
    out: set[str] = set()
    for stmt in mi.ctx.tree.body:
        if not isinstance(stmt, ast.Assign) \
                or not isinstance(stmt.value, ast.Call):
            continue
        factory = dotted_name(stmt.value.func) or ""
        if factory.split(".")[0] == "asyncio":
            continue
        if factory.split(".")[-1] not in LOCK_FACTORIES:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _held_lock(expr: ast.expr, fn: FunctionInfo,
               module_locks: set[str]) -> str | None:
    """Rendered lock name when a with-item expression holds one."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and fn.cls is not None \
            and expr.attr in fn.cls.locks:
        return f"self.{expr.attr}"
    if isinstance(expr, ast.Name) and expr.id in module_locks:
        return expr.id
    return None


def _with_body_walk(with_node: ast.With) -> Iterator[ast.AST]:
    """Every node lexically inside the with-body, not descending into
    nested defs/lambdas (deferred execution is not 'under the lock')."""
    from collections import deque

    queue = deque(with_node.body)
    while queue:
        node = queue.popleft()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        queue.extend(ast.iter_child_nodes(node))


class HoldBlockingPass(ProjectPass):
    id = "hold-blocking"
    description = ("no sleep/socket/subprocess/DB/file-I/O call reachable "
                   "(cross-module) while holding a named lock")

    #: call depth explored below the with-body (witness stays readable;
    #: real chains in this tree are 2-4 deep)
    MAX_DEPTH = 12

    def run_project(self, project: ProjectContext) -> Iterator[Finding]:
        graph = project.graph
        module_locks_cache: dict[str, set[str]] = {}
        for fn in graph.functions.values():
            if fn.relpath.startswith("models/"):
                continue  # db.writer/db.reader serialize SQLite I/O by design
            mi = graph.modules.get(fn.modkey)
            if mi is None or mi.relpath != fn.relpath:
                continue
            if mi.modkey not in module_locks_cache:
                module_locks_cache[mi.modkey] = _module_locks(mi)
            mlocks = module_locks_cache[mi.modkey]
            yield from self._check_function(fn, mi, mlocks, graph)

    def _check_function(self, fn: FunctionInfo, mi: ModuleInfo,
                        mlocks: set[str], graph) -> Iterator[Finding]:
        seen: set[str] = set()
        for node in walk_own_body(fn.node):
            if not isinstance(node, ast.With):
                continue
            locks = [lock for item in node.items
                     if (lock := _held_lock(item.context_expr, fn, mlocks))
                     is not None]
            if not locks:
                continue
            held = " + ".join(locks)
            # edges indexed by call-site node so the transitive check
            # only follows calls lexically inside THIS with-body
            edges: dict[int, list] = {}
            for callee, site, txt in fn.calls:
                edges.setdefault(id(site), []).append((callee, site, txt))
            for inner in _with_body_walk(node):
                if not isinstance(inner, ast.Call):
                    continue
                reason = _classify(inner, mi)
                if reason is not None:
                    msg = (f"blocking {reason} while holding {held} "
                           f"in {fn.short}")
                    if msg not in seen:
                        seen.add(msg)
                        yield Finding(str(mi.ctx.path), fn.relpath,
                                      inner.lineno, self.id, msg)
                    continue
                for callee, site, txt in edges.get(id(inner), ()):
                    hit = graph.reachable_blocking(
                        callee, _classify, max_depth=self.MAX_DEPTH)
                    if hit is None:
                        continue
                    path, _blk_line, blk_reason = hit
                    msg = (f"blocking {blk_reason} reachable while "
                           f"holding {held}: "
                           f"{witness([fn] + path)}")
                    if msg not in seen:
                        seen.add(msg)
                        yield Finding(str(mi.ctx.path), fn.relpath,
                                      site.lineno, self.id, msg)
