"""retry-discipline: transient-failure retries go through utils/retry.py.

The resilience layer (docs/architecture/robustness.md) funnels every
retry-with-backoff through :func:`spacedrive_tpu.utils.retry.retry_call`
— jittered, budgeted, pause/cancel-aware. An ad-hoc ``time.sleep`` inside
a loop that also catches exceptions is the classic hand-rolled retry:
un-jittered (thundering herds), unbudgeted (a dead dependency stalls the
lane forever), and deaf to Pause/Cancel (the worker sleeps out the
backoff instead of unwinding within one poll interval).

Mechanics: inside production subsystems (jobs|objects|sync|p2p), flag any
``while``/``for`` loop whose body contains BOTH

- a ``try`` statement with at least one ``except`` handler, and
- a ``time.sleep(...)`` call (any alias chain ending in ``time.sleep`` /
  ``_time.sleep``),

attributed to the sleep call's line. Pure poll loops (sleep, no except)
and pure drain loops (except, no sleep) stay silent — the combination is
what marks a retry. ``utils/retry.py`` itself lives outside the scoped
dirs, so the one sanctioned backoff loop is structurally exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

SCOPED_DIRS = ("jobs", "objects", "sync", "p2p")

SLEEP_CHAINS = ("time.sleep", "_time.sleep")


def _sleep_calls(loop: ast.While | ast.For) -> list[ast.Call]:
    out = []
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            chain = dotted_name(node.func)
            if chain in SLEEP_CHAINS:
                out.append(node)
    return out


def _has_handler(loop: ast.While | ast.For) -> bool:
    return any(isinstance(node, ast.Try) and node.handlers
               for node in ast.walk(loop))


class RetryDisciplinePass(AnalysisPass):
    id = "retry-discipline"
    description = ("ad-hoc sleep-in-loop retry patterns in jobs|objects|"
                   "sync|p2p (use utils/retry.retry_call)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*SCOPED_DIRS):
            return
        seen: set[int] = set()  # nested loops walk shared bodies once
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            if not _has_handler(node):
                continue
            for call in _sleep_calls(node):
                if call.lineno in seen:
                    continue
                seen.add(call.lineno)
                yield ctx.finding(
                    call.lineno, self.id,
                    "sleep-in-loop retry: hand-rolled backoff is "
                    "un-jittered, unbudgeted, and ignores Pause/Cancel — "
                    "use utils/retry.retry_call")
