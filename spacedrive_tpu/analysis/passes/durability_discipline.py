"""durability-discipline: artifact writes must be crash-safe.

A user-visible artifact (backup, thumbnail, trace export, preference/
config sidecar) written with a bare ``open(path, "w")`` /
``path.write_bytes(...)`` is observable half-written: a SIGKILL or a full
disk mid-write leaves a torn file that poisons every later reader. The
tempfile → fsync → rename discipline (``utils/atomic``) closes that
window — a crash leaves the old artifact or the new one, never a hybrid.

Scope: the artifact-producing subsystems — ``objects/``, ``telemetry/``,
and the package-root ``backups.py`` / ``preferences.py`` modules.

Mechanics: flag

- ``open(<target>, "<mode>")`` calls whose literal mode writes or appends
  (contains ``w`` or ``a``; ``x``/``r+`` modes are content *operations* —
  exclusive creates and in-place edits — not artifact writes), and
- ``<target>.write_bytes(...)`` / ``<target>.write_text(...)`` calls,

unless the target expression mentions a temp name (an identifier
containing ``tmp`` — the tempfile half of the discipline; the rename half
is what the atomic helpers own). Writers with a genuine reason to stream
in place (e.g. crypto_jobs' ciphertext output, unlinked on failure) carry
a line waiver: ``# lint: ok(durability-discipline)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding

#: top-level package dirs in scope (FileContext.top_dir)
SCOPE_DIRS = ("objects", "telemetry")
#: package-root modules in scope (top_dir is '' for these)
SCOPE_FILES = ("backups.py", "preferences.py")

WRITE_METHODS = {"write_bytes", "write_text"}


def _mentions_tmp(node: ast.AST) -> bool:
    """True when any identifier in the expression contains 'tmp' — the
    write is (heuristically) the tempfile half of tempfile+rename."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            name = sub.value
        if name and "tmp" in name.lower():
            return True
    return False


def _open_mode(call: ast.Call) -> str | None:
    """The literal mode of an ``open(...)`` call ('r' when omitted); None
    when the mode is dynamic (not flaggable without false positives)."""
    mode_node: ast.AST | None = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
    if mode_node is None:
        return "r"
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        return mode_node.value
    return None


class DurabilityDisciplinePass(AnalysisPass):
    id = "durability-discipline"
    description = ("artifact writes in objects|telemetry|backups|"
                   "preferences must use tempfile+rename (utils/atomic) — "
                   "a torn write survives a crash as a poisoned artifact")

    def _in_scope(self, ctx: FileContext) -> bool:
        return ctx.in_dirs(*SCOPE_DIRS) or ctx.relpath in SCOPE_FILES

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # path.write_bytes(...) / path.write_text(...)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in WRITE_METHODS:
                if not _mentions_tmp(node.func.value):
                    yield ctx.finding(
                        node.lineno, self.id,
                        f"'.{node.func.attr}()' writes an artifact in "
                        f"place — a crash mid-write leaves it torn; use "
                        f"utils/atomic (atomic_write_bytes/atomic_path) or "
                        f"waive with a rationale")
                continue
            # open(path, "w"/"a"...)
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _open_mode(node)
                if mode is None or not any(c in mode for c in "wa"):
                    continue
                target = node.args[0] if node.args else node
                if _mentions_tmp(target):
                    continue
                yield ctx.finding(
                    node.lineno, self.id,
                    f"open(..., {mode!r}) writes an artifact in place — a "
                    f"crash mid-write leaves it torn; use utils/atomic "
                    f"(atomic_write_bytes/atomic_path) or waive with a "
                    f"rationale")
