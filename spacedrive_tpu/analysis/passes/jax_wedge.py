"""jax-wedge-safety: every production jax device touchpoint must be
dominated by the wedge guard.

The tunneled device platform plugin HANGS (not errors) when its relay
dies, and it forces device backend init regardless of ``JAX_PLATFORMS``
(utils/jax_guard.py module doc). With MAX_WORKERS=1 in the job system, a
single unguarded ``jax.devices()``/``device_put`` inside a job parks the
worker — and every queued scan behind it — forever. Observed live in
rounds 4-5; this pass turns the postmortem into a mechanical invariant.

What counts as the device surface (first touch inits the backend):
- ``jax.devices(...)`` / ``jax.device_put(...)`` call sites (module alias
  or ``from jax import ...`` name);
- ``jit(...)(...)``: calling a freshly-jitted function;
- any ``jax``/``jax.numpy`` attribute use at module import time (an
  import-time jnp op wedges on *import*, before any guard can run).

What counts as a guard: a call to ``ensure_jax_safe`` (any spelling) or
to ``jax_guard.seed`` — both leave jax safe to call afterwards.

Domination is approximated lexically (guard call on an earlier line of
the same function), plus two helper forms the codebase actually uses:
- a nested function defined after the guard ran in its enclosing scope;
- a module-local helper whose every module-internal call site is itself
  guard-dominated (transitively) — e.g. ``_signatures`` in
  objects/dedup.py, called only after ``find_near_duplicates`` guarded.
A helper nobody in the module calls gets no benefit of the doubt: it is
a public entry point and must guard itself.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

#: subsystems where an unguarded touchpoint can wedge production workers
PRODUCTION_DIRS = ("jobs", "objects", "locations", "api", "server")

#: jax attributes whose call is the device surface
SURFACE_ATTRS = ("devices", "device_put")


class _Bindings:
    """Module import map: which local names reach jax, and which are guards."""

    def __init__(self, tree: ast.Module) -> None:
        self.jax_roots: set[str] = set()      # names bound to jax/jax.numpy
        self.surface_funcs: dict[str, str] = {}  # local name -> jax.<attr>
        self.jit_names: set[str] = set()      # local names for jax.jit
        self.guard_names: set[str] = set()    # ensure_jax_safe / guard seed
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax" or alias.name.startswith("jax."):
                        self.jax_roots.add(
                            alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax" or mod.startswith("jax."):
                    for alias in node.names:
                        local = alias.asname or alias.name
                        if alias.name in SURFACE_ATTRS:
                            self.surface_funcs[local] = f"jax.{alias.name}"
                        elif alias.name == "jit":
                            self.jit_names.add(local)
                        elif alias.name == "numpy":
                            self.jax_roots.add(local)
                elif mod.endswith("jax_guard"):
                    for alias in node.names:
                        if alias.name in ("ensure_jax_safe", "seed"):
                            self.guard_names.add(alias.asname or alias.name)

    # -- classification ------------------------------------------------------
    def surface_call(self, call: ast.Call) -> str | None:
        """Device-surface description for this call site, or None."""
        d = dotted_name(call.func)
        if d is not None:
            parts = d.split(".")
            if (len(parts) > 1 and parts[0] in self.jax_roots
                    and parts[-1] in SURFACE_ATTRS):
                return f"jax.{parts[-1]}()"
            if d in self.surface_funcs:
                return f"{self.surface_funcs[d]}()"
        if isinstance(call.func, ast.Call):  # jit(...)(...)
            inner = dotted_name(call.func.func)
            if inner is not None:
                parts = inner.split(".")
                # either an aliased `from jax import jit as X` name, or a
                # dotted jax.jit/jnp-root spelling
                if (inner in self.jit_names
                        or (parts[-1] == "jit"
                            and parts[0] in self.jax_roots)):
                    return "jit(...)(...)"
        return None

    def guard_call(self, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        if d is None:
            return False
        parts = d.split(".")
        if parts[-1] == "ensure_jax_safe":
            return True
        if d in self.guard_names:
            return True
        # attribute spelling of the verdict seeder: jax_guard.seed(...)
        return len(parts) >= 2 and parts[-1] == "seed" \
            and parts[-2] == "jax_guard"

    def jax_touch(self, node: ast.AST) -> bool:
        """Any expression reaching a jax-bound name (module-level check)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                d = dotted_name(sub)
                if d is not None and d.split(".")[0] in self.jax_roots:
                    return True
        return False


class _FuncInfo:
    __slots__ = ("name", "node", "surfaces", "guards", "calls",
                 "inherited_guard")

    def __init__(self, name: str, node: ast.AST) -> None:
        self.name = name
        self.node = node
        self.surfaces: list[tuple[int, str]] = []   # (lineno, description)
        self.guards: list[int] = []                 # guard-call linenos
        self.calls: list[tuple[str, int]] = []      # (callee name, lineno)
        #: nested function defined after its enclosing scope already guarded
        self.inherited_guard = False


class JaxWedgePass(AnalysisPass):
    id = "jax-wedge"
    description = ("jax device touchpoints in production modules not "
                   "dominated by ensure_jax_safe()/seed()")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*PRODUCTION_DIRS):
            return
        bindings = _Bindings(ctx.tree)
        # cheap bail: module never names jax at all
        if not (bindings.jax_roots or bindings.surface_funcs
                or bindings.jit_names):
            return

        yield from self._module_level(ctx, bindings)

        funcs: list[_FuncInfo] = []
        self._collect(ctx.tree.body, bindings, funcs)
        module_funcs = {f.name: f for f in funcs
                        if isinstance(ctx.parent(f.node), ast.Module)}
        guarded_entry = self._propagate(funcs, module_funcs)

        for info in funcs:
            entry_guarded = info.inherited_guard or (
                module_funcs.get(info.name) is info
                and guarded_entry.get(info.name, False))
            for lineno, desc in info.surfaces:
                if entry_guarded:
                    continue
                if any(g < lineno for g in info.guards):
                    continue
                yield ctx.finding(
                    lineno, self.id,
                    f"unguarded jax device access ({desc}) in "
                    f"'{info.name}' — call ensure_jax_safe() earlier in "
                    "this function, or guard every call site of it")

    # -- module import time --------------------------------------------------
    def _module_level(self, ctx: FileContext,
                      bindings: _Bindings) -> Iterator[Finding]:
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom,
                                 ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if bindings.jax_touch(stmt):
                yield ctx.finding(
                    stmt.lineno, self.id,
                    "jax use at module import time — importing this module "
                    "can init the (possibly wedged) device backend before "
                    "any guard runs; move it into a guarded function")

    # -- per-function collection --------------------------------------------
    def _collect(self, body: list[ast.stmt], bindings: _Bindings,
                 out: list[_FuncInfo]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FuncInfo(stmt.name, stmt)
                out.append(info)
                self._scan_function(stmt, bindings, info, out)
            elif isinstance(stmt, ast.ClassDef):
                self._collect(stmt.body, bindings, out)

    def _scan_function(self, func: ast.AST, bindings: _Bindings,
                       info: _FuncInfo, out: list[_FuncInfo]) -> None:
        """Walk one function's own nodes in source order; nested defs (at
        any statement depth) become separate _FuncInfo scopes so their
        touchpoints are judged against their own guards."""

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested = _FuncInfo(node.name, node)
                if info.inherited_guard or any(
                        g < node.lineno for g in info.guards):
                    nested.inherited_guard = True
                out.append(nested)
                self._scan_function(node, bindings, nested, out)
                return
            if isinstance(node, ast.Call):
                if bindings.guard_call(node):
                    info.guards.append(node.lineno)
                else:
                    desc = bindings.surface_call(node)
                    if desc is not None:
                        info.surfaces.append((node.lineno, desc))
                    callee = dotted_name(node.func)
                    if callee is not None and "." not in callee:
                        info.calls.append((callee, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in getattr(func, "body", []):
            visit(stmt)

    # -- interprocedural (module-local) guard propagation --------------------
    def _propagate(self, funcs: list[_FuncInfo],
                   module_funcs: dict[str, _FuncInfo]) -> dict[str, bool]:
        """Fixpoint: a module-level helper is guarded-on-entry when every
        module-internal call site of it is guard-dominated. No call sites →
        public entry point → not guarded."""
        call_sites: dict[str, list[tuple[_FuncInfo, int]]] = {}
        for caller in funcs:
            for callee, lineno in caller.calls:
                if callee in module_funcs:
                    call_sites.setdefault(callee, []).append((caller, lineno))

        guarded = {name: False for name in module_funcs}
        changed = True
        while changed:
            changed = False
            for name, info in module_funcs.items():
                if guarded[name]:
                    continue
                sites = call_sites.get(name)
                if not sites:
                    continue
                if all(caller.inherited_guard
                       or any(g < lineno for g in caller.guards)
                       or guarded.get(caller.name, False)
                       for caller, lineno in sites):
                    guarded[name] = True
                    changed = True
        return guarded
