"""worker-purity: pool-dispatched rspc handlers must be process-pure.

The multi-process reader pool (ISSUE 11, server/pool.py) runs
``pool=True`` query handlers inside forked worker processes against a
:class:`_ReaderNode` surrogate that carries ONLY ``libraries`` and
``data_dir``, and :class:`_ReaderLibrary` objects that carry ONLY ``id``
and a read-only ``db``. A marked handler that touches node-held mutable
state — the job manager, sync actors, the p2p manager, the event bus,
write connections — would work in-process, silently fail over out of the
pool (masking the perf win), and drift the two dispatch paths apart.
This pass makes the surrogate surface a static contract:

- inside any function decorated ``@<router>.query(..., pool=True)`` /
  ``@<router>.library_query(..., pool=True)``, attribute access on the
  **node parameter** (first positional) is limited to ``.libraries`` and
  ``.data_dir``;
- attribute access on the **library parameter** (second positional of a
  library-scoped handler) is limited to ``.db`` and ``.id``;
- ``.transaction(...)`` and write-surface calls on a DB receiver are
  findings here too (the worker's connection is ``mode=ro`` — the write
  would die at runtime; query-discipline already bans it for all query
  handlers, this pass names the pool contract).

Passing the parameters whole to a helper (``tags_for_object(library,
id)``) is allowed — the pass is module-local like its siblings; helpers
that reach beyond ``library.db`` fail at runtime in the worker and fall
over to in-process dispatch, which the ``sd_serve_worker_requests_total
{outcome="failover"}`` series makes visible.

Scoped to ``api/`` — the only place rspc handlers live.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name
from .query_discipline import WRITE_ATTRS, _is_db_receiver

#: the _ReaderNode surface (server/pool.py)
NODE_ALLOWED = frozenset({"libraries", "data_dir"})
#: the _ReaderLibrary surface
LIBRARY_ALLOWED = frozenset({"db", "id"})

QUERY_DECORATORS = ("query", "library_query")


def _pool_decorator(node: ast.FunctionDef | ast.AsyncFunctionDef
                    ) -> tuple[str, bool] | None:
    """(decorator name, library-scoped) when this is a ``pool=True``
    query handler; None otherwise."""
    for dec in node.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        func = dec.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in QUERY_DECORATORS:
            continue
        pool = any(kw.arg == "pool"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True
                   for kw in dec.keywords)
        if not pool:
            continue
        # scope may arrive as the keyword OR the second positional of
        # router.query(key, scope, ...) — both must bind library_param
        library_scoped = func.attr == "library_query" or any(
            kw.arg == "scope" and isinstance(kw.value, ast.Constant)
            and kw.value.value == "library" for kw in dec.keywords) or (
            len(dec.args) >= 2 and isinstance(dec.args[1], ast.Constant)
            and dec.args[1].value == "library")
        return func.attr, library_scoped
    return None


class WorkerPurityPass(AnalysisPass):
    id = "worker-purity"
    description = ("pool-dispatched query handlers touching node-held "
                   "mutable state (workers see only node.libraries/"
                   "node.data_dir and library.db/library.id)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs("api"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            marked = _pool_decorator(node)
            if marked is None:
                continue
            decorator, library_scoped = marked
            params = [a.arg for a in node.args.args]
            node_param = params[0] if params else None
            library_param = (params[1]
                             if library_scoped and len(params) > 1 else None)
            for inner in ast.walk(node):
                if isinstance(inner, ast.Attribute) \
                        and isinstance(inner.value, ast.Name):
                    owner = inner.value.id
                    if owner == node_param \
                            and inner.attr not in NODE_ALLOWED:
                        yield ctx.finding(
                            inner.lineno, self.id,
                            f"'{owner}.{inner.attr}' in pool-dispatched "
                            f"{decorator} handler '{node.name}' — workers "
                            f"see only node.libraries/node.data_dir "
                            f"(node-held state stays in the node process)")
                    elif owner == library_param \
                            and inner.attr not in LIBRARY_ALLOWED:
                        yield ctx.finding(
                            inner.lineno, self.id,
                            f"'{owner}.{inner.attr}' in pool-dispatched "
                            f"{decorator} handler '{node.name}' — worker "
                            f"libraries carry only .db (read-only) and .id")
                if isinstance(inner, ast.Call) \
                        and isinstance(inner.func, ast.Attribute):
                    chain = dotted_name(inner.func)
                    if chain is None:
                        continue
                    attr = inner.func.attr
                    if attr == "transaction" or (attr in WRITE_ATTRS
                                                 and _is_db_receiver(chain)):
                        yield ctx.finding(
                            inner.lineno, self.id,
                            f"'{chain}()' in pool-dispatched {decorator} "
                            f"handler '{node.name}' — the worker's "
                            f"connection is read-only (mode=ro)")
