"""lock-discipline: module-level mutable state honors its sibling lock.

The ``_STATE`` idiom (utils/jax_guard.py, objects/media/thumbnail.py): a
module-level dict/list/set guarded by a module-level ``threading.Lock``.
The idiom only works when *every* mutation happens under ``with <lock>:``
— one bare mutation and the memoized verdict / probe dedup it protects
can race (two concurrent first-touch probes, a torn check-then-set).

This pass fires only in modules that define BOTH a module-level lock and
module-level mutable literal state, and flags mutations of that state
(subscript stores/deletes, augmented assigns, and mutating method calls
like ``.update``/``.add``/``.append``) that are not lexically inside a
``with`` block naming one of the module's locks. Module-top-level
mutations (single-threaded import time) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

MUTATOR_METHODS = {
    "add", "append", "extend", "insert", "update", "setdefault",
    "pop", "popitem", "clear", "remove", "discard",
}

MUTABLE_FACTORIES = {"dict", "list", "set", "defaultdict", "deque",
                     "Counter", "OrderedDict"}


def _module_assignments(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(lock names, mutable state names) assigned at module level."""
    locks: set[str] = set()
    mutables: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            name, value = stmt.target.id, stmt.value
        else:
            continue
        if isinstance(value, ast.Call):
            d = dotted_name(value.func)
            if d is not None:
                leaf = d.split(".")[-1]
                if leaf in ("Lock", "RLock"):
                    locks.add(name)
                elif leaf in MUTABLE_FACTORIES:
                    mutables.add(name)
        elif isinstance(value, (ast.Dict, ast.List, ast.Set,
                                ast.DictComp, ast.ListComp, ast.SetComp)):
            mutables.add(name)
    return locks, mutables


class LockDisciplinePass(AnalysisPass):
    id = "lock-discipline"
    description = ("module-level mutable state mutated outside its "
                   "sibling lock's with-block")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        locks, mutables = _module_assignments(ctx.tree)
        if not locks or not mutables:
            return
        findings: list[Finding] = []
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self._visit(ctx, stmt, locks, mutables, lock_depth=0,
                            findings=findings)
        yield from findings

    def _visit(self, ctx: FileContext, node: ast.AST, locks: set[str],
               mutables: set[str], lock_depth: int,
               findings: list[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # deferred execution: a function DEFINED under `with lock:`
            # runs later, when the lock is long released — its body gets
            # no credit for the definition site's lock depth
            lock_depth = 0
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            if any(self._is_lock_expr(item.context_expr, locks)
                   for item in node.items):
                lock_depth += 1
        else:
            target = self._mutation_target(node, mutables)
            if target is not None and lock_depth == 0:
                findings.append(ctx.finding(
                    node.lineno, self.id,
                    f"module state '{target}' mutated outside "
                    f"'with <{'/'.join(sorted(locks))}>:' — the sibling "
                    "lock exists precisely for this state"))
        for child in ast.iter_child_nodes(node):
            self._visit(ctx, child, locks, mutables, lock_depth, findings)

    def _is_lock_expr(self, expr: ast.AST, locks: set[str]) -> bool:
        d = dotted_name(expr)
        return d is not None and d.split(".")[-1] in locks

    def _mutation_target(self, node: ast.AST,
                         mutables: set[str]) -> str | None:
        def sub_root(target: ast.AST) -> str | None:
            if isinstance(target, ast.Subscript) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id in mutables:
                return target.value.id
            return None

        if isinstance(node, (ast.Assign,)):
            for target in node.targets:
                root = sub_root(target)
                if root is not None:
                    return root
        elif isinstance(node, ast.AugAssign):
            root = sub_root(node.target)
            if root is not None:
                return root
            if isinstance(node.target, ast.Name) \
                    and node.target.id in mutables:
                return node.target.id
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                root = sub_root(target)
                if root is not None:
                    return root
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in mutables:
            return node.func.value.id
        return None
