"""waiver-ledger: concurrency waivers and robustness.md must agree.

A scoped ``lint: ok(lockset)``-style waiver comment is an argument that
a flagged shape is safe — and arguments belong where reviewers read
them, not buried in a trailing comment. The ledger in docs/architecture/robustness.md (the
"known waivers" table) is that place. This meta-pass enforces the
contract in both directions, the way the /metrics drift gate pins the
telemetry doc:

- every in-tree waiver naming a concurrency pass (``lockset``,
  ``hold-blocking``, ``loop-blocking``, ``thread-role``) must have a
  ledger row whose site names the waiver's file;
- every ledger row must still correspond to at least one such waiver in
  the named file — a fixed site whose row lingers is a stale argument
  that will mislead the next reader (and rows for files that no longer
  exist are flagged too).

Fixture trees have no robustness.md; the pass is silent then, so the
red/green fixtures of the other passes stay self-contained. The ledger
is looked up at ``<root>/docs/architecture/robustness.md`` first and
``<root>/../docs/architecture/robustness.md`` second (the real layout:
the scan root is the package directory, docs live beside it).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Iterator

from ..engine import WAIVER_RE, FileContext, Finding, ProjectContext, \
    ProjectPass

#: the pass families whose waivers demand a written argument
LEDGER_PASSES = frozenset(
    {"lockset", "hold-blocking", "loop-blocking", "thread-role"})

_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _ledger_path(root: Path) -> Path | None:
    for base in (root, root.parent):
        cand = base / "docs" / "architecture" / "robustness.md"
        if cand.is_file():
            return cand
    return None


def parse_ledger(text: str) -> list[tuple[str, str]]:
    """(relpath, row-text) per known-waiver table row. The table is
    recognized by its header (a markdown row containing both ``site``
    and ``waived``); the site cell's first backticked token is the
    file path."""
    rows: list[tuple[str, str]] = []
    in_table = False
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        low = stripped.lower()
        if "site" in low and "waived" in low:
            in_table = True
            continue
        if not in_table or set(stripped) <= {"|", "-", " ", ":"}:
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        m = _BACKTICK_RE.search(cells[0])
        if m:
            rows.append((m.group(1), stripped))
    return rows


def _file_waivers(ctx: FileContext) -> Iterator[tuple[int, frozenset[str]]]:
    """(lineno, ledger-relevant pass ids) per scoped waiver comment."""
    for i, line in enumerate(ctx.lines, start=1):
        m = WAIVER_RE.search(line)
        if m is None or m.group(1) is None:
            continue  # no waiver, or the blanket form (hygiene-pass use)
        ids = frozenset(p.strip() for p in m.group(1).split(",")
                        if p.strip()) & LEDGER_PASSES
        if ids:
            yield i, ids


class WaiverLedgerPass(ProjectPass):
    id = "waiver-ledger"
    description = ("every concurrency-pass waiver has a robustness.md "
                   "ledger row and no ledger row is stale")

    def run_project(self, project: ProjectContext) -> Iterator[Finding]:
        ledger = _ledger_path(project.root)
        if ledger is None:
            return  # fixture tree: nothing to cross-check against
        try:
            rows = parse_ledger(ledger.read_text())
        except OSError:
            return
        ledger_files = {relpath for relpath, _row in rows}
        waived_files: set[str] = set()
        for relpath, ctx in sorted(project.files.items()):
            for lineno, ids in _file_waivers(ctx):
                waived_files.add(relpath)
                if relpath not in ledger_files:
                    yield Finding(
                        str(ctx.path), relpath, lineno, self.id,
                        f"waiver for {'/'.join(sorted(ids))} has no "
                        f"known-waiver ledger row in robustness.md "
                        f"(add `{relpath}` to the table, with the "
                        f"argument)")
        for relpath, _row in rows:
            if relpath in waived_files:
                continue
            ctx = project.files.get(relpath)
            if ctx is not None:
                yield Finding(
                    str(ctx.path), relpath, 1, self.id,
                    f"stale known-waiver ledger row: `{relpath}` has no "
                    f"{'/'.join(sorted(LEDGER_PASSES))} waiver left — "
                    f"drop the robustness.md row")
            else:
                yield Finding(
                    relpath, relpath, 0, self.id,
                    f"stale known-waiver ledger row: `{relpath}` is not "
                    f"in the scanned tree — drop the robustness.md row")
