"""loop-blocking: async-blocking upgraded to cross-module reachability.

The per-file ``async-blocking`` pass gates the LEXICAL body of every
``async def`` in api/ server/ p2p/ — it cannot see a sync
``socket.recv`` two calls below a helper in another module. This pass
closes that gap: starting from every ``async def`` root in the
event-loop subsystems it follows resolved call edges (the project
graph) and reports any blocking primitive reachable at depth >= 1,
anchored at the root's own call site with the full witness path.

Division of labor (so one defect is one finding):

- depth 0 (a blocking call lexically inside the async body) stays
  ``async-blocking``'s report;
- the bodies of OTHER event-loop-subsystem async defs are skipped as
  holders too — their own lexical sins are again ``async-blocking``'s
  — but the walk still descends *through* them, so a chain
  ``handler -> other_handler -> sync_helper -> time.sleep`` is found
  exactly once, here;
- spawn edges (``run_in_executor``, ``Thread(target=...)``) are not
  call edges, so the sanctioned offload idiom never reports.

DB calls are included: a ``db.query()`` on the loop stalls every
connected peer for the full SQLite round-trip, which is exactly the
WAN-soak tail shape PR 13 chased.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..callgraph import (EVENT_LOOP_DIRS, FunctionInfo, ModuleInfo,
                         blocking_call_reason, top_dir, witness)
from ..engine import Finding, ProjectContext, ProjectPass


def _classify(call: ast.Call, mi: ModuleInfo) -> str | None:
    return blocking_call_reason(call, mi, include_db=True,
                                include_open=False)


def _is_loop_async(fn: FunctionInfo) -> bool:
    return fn.is_async and top_dir(fn.relpath) in EVENT_LOOP_DIRS


class LoopBlockingPass(ProjectPass):
    id = "loop-blocking"
    description = ("no blocking call reachable (cross-module, depth>=1) "
                   "from an async def in api|server|p2p")

    MAX_DEPTH = 12

    def run_project(self, project: ProjectContext) -> Iterator[Finding]:
        graph = project.graph
        for fn in graph.functions.values():
            if not _is_loop_async(fn):
                continue
            mi = graph.modules.get(fn.modkey)
            if mi is None or mi.relpath != fn.relpath:
                continue
            seen: set[str] = set()
            for callee, site, _txt in fn.calls:
                hit = graph.reachable_blocking(
                    callee, _classify, max_depth=self.MAX_DEPTH,
                    skip_holder=_is_loop_async)
                if hit is None:
                    continue
                path, _blk_line, reason = hit
                msg = (f"event-loop blocking: {reason} reachable from "
                       f"async {fn.short} via {witness(path)}")
                if msg in seen:
                    continue
                seen.add(msg)
                yield Finding(str(mi.ctx.path), fn.relpath, site.lineno,
                              self.id, msg)
