"""cardinality-discipline: label values on telemetry families must be
drawn from bounded sets.

ISSUE 20 satellite. The registry caps label *names* at declaration, but
nothing stops a record site from feeding an unbounded *value* — a file
path, a UUID, an error string — into ``family.inc(peer=...)``, and one
such site grows the registry (and every scrape) without limit. The SLO
engine's per-tenant families made the discipline load-bearing: tenant
labels are bounded only because ``slo.tenant_label`` LRU-caps them.

Scoped to the production subsystems (jobs|sync|p2p|server|api). Within
a file, a *metric handle* is any name assigned from a
``<module>.counter/gauge/histogram(...)`` call; every keyword argument
on a ``handle.inc/set/observe/labels(...)`` call is a label value and
must be **bounded**:

- a string literal (closed literal sets: ``outcome="ok"``);
- a conditional/boolean of bounded parts (``"hit" if ok else "miss"``);
- ``str(x)`` of a name/attribute/literal (small-int enums:
  ``lane=str(i)``, ``worker=str(slot)``);
- a call to a bounding helper — a function whose name ends in
  ``peer_label`` / ``tenant_label`` / ``route_class`` (the hash-capped
  and whitelist helpers);
- an attribute whose name is UPPERCASE (class-constant registries:
  ``job.NAME``, ``job.LANE``), contains ``label`` (a value that was
  already bounded at construction), or is ``slot`` (pool slot indices);
- a name whose in-file bindings are all bounded, or that has no in-file
  binding at all (parameters and loop targets are the *caller's*
  contract — the pass checks record sites, not whole-program flow).

Anything else — f-strings, concatenation, ``.format``, arbitrary calls,
subscripts — is flagged. Genuine closed sets the rules cannot see get
an explicit ``# lint: ok(cardinality-discipline)`` waiver on the line,
which the waiver ledger keeps auditable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

SCOPED_DIRS = ("jobs", "sync", "p2p", "server", "api")

#: factory methods that mint a metric handle (same set as
#: telemetry-discipline's vocabulary rule)
METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

#: handle methods whose keyword arguments are label values
RECORD_METHODS = frozenset({"inc", "set", "observe", "labels"})

#: helper-name suffixes that bound their return value by construction
BOUNDING_SUFFIXES = ("peer_label", "tenant_label", "route_class")

#: attribute names that carry an already-bounded value
BOUNDED_ATTRS = frozenset({"slot"})


def _metric_handles(tree: ast.Module) -> set[str]:
    """Names assigned (anywhere in the file) from a
    ``<module>.counter/gauge/histogram(...)`` call."""
    handles: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        chain = dotted_name(value.func)
        if chain is None or "." not in chain:
            continue
        if chain.rsplit(".", 1)[-1] not in METRIC_FACTORIES:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                handles.add(target.id)
    return handles


def _name_bindings(tree: ast.Module) -> dict[str, list[ast.expr]]:
    """name -> every expression a plain ``name = expr`` assigns in the
    file (coarse, flow-insensitive — like the timer-name collection in
    telemetry-discipline)."""
    bindings: dict[str, list[ast.expr]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                bindings.setdefault(target.id, []).append(node.value)
    return bindings


class _Boundedness:
    def __init__(self, bindings: dict[str, list[ast.expr]]) -> None:
        self.bindings = bindings
        self._visiting: set[str] = set()

    def bounded(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (str, int, bool))
        if isinstance(node, ast.IfExp):
            return self.bounded(node.body) and self.bounded(node.orelse)
        if isinstance(node, ast.BoolOp):
            return all(self.bounded(v) for v in node.values)
        if isinstance(node, ast.Attribute):
            return (node.attr.isupper()
                    or "label" in node.attr.lower()
                    or node.attr in BOUNDED_ATTRS)
        if isinstance(node, ast.Name):
            exprs = self.bindings.get(node.id)
            if not exprs:
                # parameter / loop target / comprehension variable: the
                # value is the caller's contract, not this site's
                return True
            if node.id in self._visiting:
                return True  # self-referential rebind (x = x or "d")
            self._visiting.add(node.id)
            try:
                return all(self.bounded(e) for e in exprs)
            finally:
                self._visiting.discard(node.id)
        if isinstance(node, ast.Call):
            chain = dotted_name(node.func) or ""
            if chain.endswith(BOUNDING_SUFFIXES):
                return True
            if chain == "str" and len(node.args) == 1 and isinstance(
                    node.args[0], (ast.Name, ast.Attribute, ast.Constant)):
                return True
            return False
        return False


class CardinalityDisciplinePass(AnalysisPass):
    id = "cardinality-discipline"
    description = ("label values recorded on telemetry families in "
                   "jobs|sync|p2p|server|api must come from bounded sets "
                   "(literals, UPPERCASE registries, *_label helpers) — "
                   "an unbounded label value grows the registry forever")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*SCOPED_DIRS):
            return
        handles = _metric_handles(ctx.tree)
        if not handles:
            return
        check = _Boundedness(_name_bindings(ctx.tree))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in RECORD_METHODS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in handles):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **labels splat: the dict's builder owns it
                if not check.bounded(kw.value):
                    yield ctx.finding(
                        kw.value.lineno, self.id,
                        f"label {kw.arg!r} on {func.value.id}.{func.attr} "
                        f"is not drawn from a bounded set — hash/cap it "
                        f"(slo.tenant_label, mesh.peer_label) or waive "
                        f"with a comment explaining the bound")
