"""query-discipline: query-scope rspc handlers must be read-only.

The serving tier's whole performance story (ISSUE 10) rests on queries
riding the WAL *reader* connection — never queueing behind the writer
lock, never opening transactions. A ``router.query`` handler that writes
would (a) contend the single-writer discipline from the rspc worker
pool, (b) break the HTTP GET = side-effect-free contract the shell
enforces (`server/shell.py` routes GETs to queries only), and (c) make
request telemetry lie about what the read path costs. Mutations exist
for exactly this; move the write there.

Mechanics: inside any function decorated ``@<router>.query(...)`` or
``@<router>.library_query(...)`` (the api/routers mount idiom, including
helpers nested within the handler), flag

- any ``.transaction(...)`` call — a query has no business being atomic
  over writes it must not make;
- write-surface calls (execute/executemany/insert/insert_ignore/
  insert_many/update/upsert/delete) whose receiver is a DB handle (a
  name chain ending in ``db``/``database``), so dict ``.update()`` and
  manager-layer ``.delete()`` calls don't trip it.

Scoped to ``api/`` — the only place rspc handlers live.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

QUERY_DECORATORS = ("query", "library_query")

WRITE_ATTRS = {"execute", "executemany", "insert", "insert_ignore",
               "insert_many", "update", "upsert", "delete"}


def _is_db_receiver(chain: str) -> bool:
    """'db', 'library.db', 'node.library.db', … — the handle naming
    idiom (same classifier as the pipeline-ordering pass)."""
    head = chain.rsplit(".", 1)[0] if "." in chain else ""
    last = head.rsplit(".", 1)[-1] if head else ""
    return last in ("db", "database")


def _query_decorator(node: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
    """The decorator name when this function is a query-scope handler."""
    for dec in node.decorator_list:
        call = dec if isinstance(dec, ast.Call) else None
        func = call.func if call is not None else dec
        if isinstance(func, ast.Attribute) and func.attr in QUERY_DECORATORS:
            return func.attr
    return None


class QueryDisciplinePass(AnalysisPass):
    id = "query-discipline"
    description = ("DB transactions/writes inside query-scope rspc "
                   "handlers (queries are read-only; writes belong to "
                   "mutations)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs("api"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            decorator = _query_decorator(node)
            if decorator is None:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) \
                        or not isinstance(call.func, ast.Attribute):
                    continue
                chain = dotted_name(call.func)
                if chain is None:
                    continue
                attr = call.func.attr
                if attr == "transaction":
                    yield ctx.finding(
                        call.lineno, self.id,
                        f"'{chain}()' in {decorator} handler "
                        f"'{node.name}' — queries must not open "
                        f"transactions (use a mutation)")
                elif attr in WRITE_ATTRS and _is_db_receiver(chain):
                    yield ctx.finding(
                        call.lineno, self.id,
                        f"DB write '{chain}()' in {decorator} handler "
                        f"'{node.name}' — queries are read-only (use a "
                        f"mutation)")
