"""telemetry-discipline: ad-hoc timing goes through spans; metric names
match the vocabulary.

Two rules, scoped to the production subsystems
(jobs|objects|pipeline|sync|p2p):

1. **No hand-rolled stage timing into report/metric dicts.** A
   ``time.time()``/``time.perf_counter()`` delta stored into a dict —
   ``batch["gather_s"] = time.perf_counter() - t0`` or
   ``{"media_time": time.perf_counter() - t0}`` — is exactly the
   bench-only instrumentation ISSUE 5 replaced: it cannot appear in the
   job trace, cannot be scraped, and silently drifts from the span data
   the report now reads. Wrap the timed section in
   ``telemetry.span(...)`` and store ``sp.duration_s`` instead.
   (Deltas used for log lines, rate math, or local variables stay
   legal — only dict stores are flagged, because dicts are how timings
   reach reports and metrics.)

2. **Metric names match ``^sd_[a-z0-9_]+$``.** Any
   ``*.counter("name", ...)`` / ``*.gauge(...)`` / ``*.histogram(...)``
   call whose first argument is a string literal outside the vocabulary
   is flagged — the registry would reject it at runtime, but only on the
   first code path that reaches it; the pass fails the tree at commit
   time instead.

Mechanics for rule 1: within each file, names bound by a plain
``name = time.perf_counter()`` / ``time.time()`` assignment are timer
names; a ``BinOp`` subtraction with a timer call or timer name as an
operand is a *delta*; a delta is flagged when it (or an expression
containing it) is assigned to a Subscript target, augmented-assigned to
one, or appears as a value in a dict literal.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

SCOPED_DIRS = ("jobs", "objects", "pipeline", "sync", "p2p")

#: call chains that produce a wall-clock timestamp (rule 1)
TIME_CHAINS = frozenset({
    "time.time", "time.perf_counter",
    "_time.time", "_time.perf_counter",
    "perf_counter",  # from time import perf_counter
})

#: method names that declare/resolve a metric family (rule 2)
METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

METRIC_NAME_RE = re.compile(r"^sd_[a-z0-9_]+$")


def _is_time_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in TIME_CHAINS)


def _timer_names(tree: ast.Module) -> set[str]:
    """Names bound (anywhere in the file) by ``name = time.perf_counter()``
    — coarse but effective: a name that EVER holds a timestamp makes any
    subtraction against it a timing delta."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_time_call(node.value):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
    return names


#: value-preserving wrappers a stored delta commonly hides in
#: (``d["x"] = round(now - t0, 3)`` is still hand-rolled report timing)
_TRANSPARENT_CALLS = frozenset({"round", "min", "max", "abs", "float"})


def _walk_no_calls(node: ast.AST):
    """Walk ``node`` without descending into Call arguments — EXCEPT
    value-preserving wrappers (round/min/max/abs/float), which pass the
    delta through to the store. A delta passed into any other function
    (``score(now - t0)``) is that callee's business — only a delta that
    IS the stored value (possibly wrapped in arithmetic or a transparent
    call) marks hand-rolled report timing."""
    yield node
    if isinstance(node, ast.Call):
        if not (isinstance(node.func, ast.Name)
                and node.func.id in _TRANSPARENT_CALLS):
            return
    for child in ast.iter_child_nodes(node):
        yield from _walk_no_calls(child)


def _contains_delta(node: ast.AST, timers: set[str]) -> ast.BinOp | None:
    """First Sub BinOp under ``node`` (outside call args) with a
    timestamp operand."""
    for sub in _walk_no_calls(node):
        if not (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub)):
            continue
        for operand in (sub.left, sub.right):
            if _is_time_call(operand):
                return sub
            if isinstance(operand, ast.Name) and operand.id in timers:
                return sub
    return None


class TelemetryDisciplinePass(AnalysisPass):
    id = "telemetry-discipline"
    description = ("perf_counter/time.time deltas stored into report/metric "
                   "dicts (use telemetry.span), and metric names outside "
                   "^sd_[a-z0-9_]+$ in jobs|objects|pipeline|sync|p2p")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*SCOPED_DIRS):
            return
        timers = _timer_names(ctx.tree)

        for node in ast.walk(ctx.tree):
            # rule 1a: d["k"] = <delta> / d["k"] += <delta>
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                if any(isinstance(t, ast.Subscript) for t in targets):
                    delta = _contains_delta(node.value, timers)
                    if delta is not None:
                        yield ctx.finding(
                            delta.lineno, self.id,
                            "timing delta stored into a dict: route the "
                            "measurement through telemetry.span and store "
                            "span.duration_s")
            # rule 1b: {"k": <delta>} dict literals (report/metadata shapes)
            elif isinstance(node, ast.Dict):
                for value in node.values:
                    if value is None:
                        continue  # **splat
                    delta = _contains_delta(value, timers)
                    if delta is not None:
                        yield ctx.finding(
                            delta.lineno, self.id,
                            "timing delta in a dict literal: route the "
                            "measurement through telemetry.span and store "
                            "span.duration_s")
            # rule 2: metric-name vocabulary at declaration sites
            elif isinstance(node, ast.Call):
                chain = dotted_name(node.func)
                if chain is None:
                    continue
                method = chain.rsplit(".", 1)[-1]
                if method not in METRIC_FACTORIES or "." not in chain:
                    # bare counter()/gauge() names are too generic to
                    # attribute (collections.Counter locals etc.); the
                    # codebase declares via <module>.counter(...)
                    continue
                if not node.args:
                    continue
                first = node.args[0]
                if isinstance(first, ast.Constant) \
                        and isinstance(first.value, str) \
                        and not METRIC_NAME_RE.match(first.value):
                    yield ctx.finding(
                        node.lineno, self.id,
                        f"metric name {first.value!r} must match "
                        f"{METRIC_NAME_RE.pattern}")
