"""The original ``utils/lint.py`` defect classes as engine passes.

Message text is kept byte-identical to the old linter so the
``python -m spacedrive_tpu.utils.lint`` shim (and its tests) see the
same output through the new engine.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding

_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()

    def add_annotation_strings(node: ast.AST | None) -> None:
        # quoted annotations ("Library") reference names the AST only sees
        # as string constants — count their identifiers as used
        for sub in ast.walk(node) if node is not None else ():
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                used.update(_IDENT.findall(sub.value))

    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_annotation_strings(node.returns)
            for arg in (node.args.args + node.args.posonlyargs
                        + node.args.kwonlyargs
                        + ([node.args.vararg] if node.args.vararg else [])
                        + ([node.args.kwarg] if node.args.kwarg else [])):
                add_annotation_strings(arg.annotation)
        elif isinstance(node, ast.AnnAssign):
            add_annotation_strings(node.annotation)
    return used


class UnusedImportPass(AnalysisPass):
    id = "unused-import"
    description = "imports never referenced (package __init__ re-exports ok)"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        used = _used_names(ctx.tree)
        exported: set[str] = set()
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "__all__"
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        exported.add(elt.value)
        if ctx.path.name == "__init__.py":  # packages re-export by importing
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if getattr(node, "module", None) == "__future__":
                continue
            for alias in node.names:
                name = (alias.asname or alias.name).split(".")[0]
                if alias.name == "*":
                    continue
                if name in used or name in exported:
                    continue
                yield ctx.finding(
                    node.lineno, self.id,
                    f"unused import '{alias.asname or alias.name}'")


class BareExceptPass(AnalysisPass):
    id = "bare-except"
    description = "bare 'except:' clauses (catch Exception or narrower)"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield ctx.finding(node.lineno, self.id,
                                  "bare 'except:' (catch Exception or "
                                  "narrower)")


class DuplicateDefPass(AnalysisPass):
    id = "duplicate-def"
    description = "duplicate top-level definitions (silent shadowing)"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        seen: dict[str, int] = {}
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if node.name in seen:
                    yield ctx.finding(
                        node.lineno, self.id,
                        f"duplicate top-level definition '{node.name}' "
                        f"(first at line {seen[node.name]})")
                seen.setdefault(node.name, node.lineno)


LEGACY_PASSES = (UnusedImportPass, BareExceptPass, DuplicateDefPass)
