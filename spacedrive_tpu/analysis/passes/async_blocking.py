"""async-blocking: no synchronous stalls on the event loops.

The api/, server/, and p2p/ subsystems run single asyncio loops; one
blocking call inside an ``async def`` freezes every connection, pairing
handshake, and transfer sharing that loop — the async flavor of the same
liveness failure the jax wedge guard exists for.

Flagged inside ``async def`` bodies in those subsystems:
- ``subprocess.run/call/check_call/check_output``;
- ``time.sleep`` (asyncio.sleep exists for a reason);
- ``socket.create_connection`` (blocking connect+DNS);
- any ``requests.*`` call (the whole library is synchronous);
- ``Path.read_bytes/read_text/write_bytes/write_text``-shaped attribute
  calls (unbounded disk IO on the loop);
- unbounded ``.result()`` / ``.join()`` — zero-argument calls that can
  wait forever (``await``ing a future or a bounded timeout is fine;
  ``str.join`` always takes an argument, so it never matches).

Nested *sync* ``def``s inside an async body are NOT scanned: that is the
``run_in_executor`` idiom (p2p/manager.py's ``_lookup``), where blocking
work is exactly what belongs there.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

ASYNC_DIRS = ("api", "server", "p2p")

BLOCKING_DOTTED = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "time.sleep", "socket.create_connection",
}

BLOCKING_METHODS = {"read_bytes", "read_text", "write_bytes", "write_text"}

UNBOUNDED_METHODS = {"result", "join"}


class AsyncBlockingPass(AnalysisPass):
    id = "async-blocking"
    description = ("blocking calls inside async def bodies in api/, "
                   "server/, p2p/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*ASYNC_DIRS):
            return
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan_async(ctx, node, findings)
        # ast.walk finds nested async defs too; scanning is scoped to each
        # function's own body, so nothing double-reports
        yield from findings

    def _scan_async(self, ctx: FileContext, func: ast.AsyncFunctionDef,
                    findings: list[Finding]) -> None:
        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # sync helpers run in executors; nested async defs
                # are scanned as their own functions by run()
            if isinstance(node, ast.Call):
                reason = self._blocking_reason(node)
                if reason is not None:
                    findings.append(ctx.finding(
                        node.lineno, self.id,
                        f"blocking call {reason} inside "
                        f"'async def {func.name}' — use the asyncio "
                        "equivalent or run_in_executor"))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in func.body:
            visit(stmt)

    def _blocking_reason(self, call: ast.Call) -> str | None:
        d = dotted_name(call.func)
        if d is not None:
            if d in BLOCKING_DOTTED:
                return f"{d}()"
            if d.split(".")[0] == "requests":
                return f"{d}() (requests is synchronous)"
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in BLOCKING_METHODS:
                return f".{attr}()"
            if (attr in UNBOUNDED_METHODS and not call.args
                    and not call.keywords):
                return f"unbounded .{attr}()"
        return None
