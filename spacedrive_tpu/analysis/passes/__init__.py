"""Pass registry: all built-in analysis passes in execution order.

Adding a pass = write a module with an :class:`AnalysisPass` subclass,
import it here, append to the tuple, run ``--update-baseline`` if the
tree has pre-existing findings. See docs/static-analysis.md.
"""

from __future__ import annotations

from ..engine import AnalysisPass
from .async_blocking import AsyncBlockingPass
from .cardinality_discipline import CardinalityDisciplinePass
from .commit_discipline import CommitDisciplinePass
from .durability_discipline import DurabilityDisciplinePass
from .hold_blocking import HoldBlockingPass
from .jax_wedge import JaxWedgePass
from .legacy import BareExceptPass, DuplicateDefPass, UnusedImportPass
from .lock_discipline import LockDisciplinePass
from .lockset import LocksetPass
from .loop_blocking import LoopBlockingPass
from .pipeline_ordering import PipelineOrderingPass
from .query_discipline import QueryDisciplinePass
from .queue_discipline import QueueDisciplinePass
from .replica_purity import ReplicaPurityPass
from .resource_leak import ResourceLeakPass
from .retry_discipline import RetryDisciplinePass
from .swallowed import SwallowedExceptionPass
from .telemetry_discipline import TelemetryDisciplinePass
from .thread_role import ThreadRolePass
from .waiver_ledger import WaiverLedgerPass
from .worker_purity import WorkerPurityPass

REGISTRY: tuple[type[AnalysisPass], ...] = (
    # legacy hygiene gates (formerly utils/lint.py)
    UnusedImportPass,
    BareExceptPass,
    DuplicateDefPass,
    # the liveness/concurrency invariants
    JaxWedgePass,
    AsyncBlockingPass,
    LockDisciplinePass,
    LocksetPass,
    ResourceLeakPass,
    SwallowedExceptionPass,
    PipelineOrderingPass,
    CommitDisciplinePass,
    RetryDisciplinePass,
    TelemetryDisciplinePass,
    CardinalityDisciplinePass,
    QueueDisciplinePass,
    DurabilityDisciplinePass,
    QueryDisciplinePass,
    WorkerPurityPass,
    ReplicaPurityPass,
    # whole-program passes (ISSUE 16): run last, over the project graph
    HoldBlockingPass,
    LoopBlockingPass,
    ThreadRolePass,
    WaiverLedgerPass,
)


def all_passes() -> list[AnalysisPass]:
    return [cls() for cls in REGISTRY]
