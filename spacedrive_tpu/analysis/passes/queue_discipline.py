"""queue-discipline: in-memory queues on the ingest/dispatch paths are bounded.

ISSUE 8's overload postmortem in one sentence: every unbounded queue
between a peer and a durable write is a memory leak with a workload
attached. The admission/lane layer (sync/admission.py, sync/lanes.py)
bounds the CRDT receive path by construction; this pass keeps the
invariant from regressing anywhere in the production subsystems that sit
on those paths (``sync|p2p|jobs|pipeline``): a ``queue.Queue()`` /
``collections.deque()`` constructed **without an explicit bound** is a
finding.

Mechanics: flag calls to ``queue.Queue`` / ``queue.LifoQueue`` /
``queue.PriorityQueue`` (dotted or imported bare) whose ``maxsize`` is
absent, ``0``, or negative (the stdlib's "unbounded" spellings), any use
of ``queue.SimpleQueue`` (it has no bound at all), and ``deque`` calls
with no ``maxlen``. Bare names only count when the file actually imports
them from ``queue``/``collections`` — a local helper named ``deque`` is
not a queue. A deliberate unbounded queue states its displacement
argument in a comment and carries a scoped waiver
(``# lint: ok(queue-discipline)``), e.g. the jobs manager's overflow
deque (bounded by job-hash dedup, persisted as Queued rows).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

SCOPED_DIRS = ("sync", "p2p", "jobs", "pipeline")

#: queue.* constructors taking maxsize (first positional or keyword)
SIZED = {"Queue", "LifoQueue", "PriorityQueue"}
#: never boundable — any construction is a finding
UNSIZABLE = {"SimpleQueue"}


def _bare_imports(tree: ast.Module) -> dict[str, str]:
    """name -> origin module for ``from queue import Queue``-style imports
    (aliases resolved to the imported symbol's real name)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "queue", "collections"):
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return out


def _is_unbounded_literal(node: ast.expr) -> bool:
    """The stdlib's explicit "no bound" spellings: 0, negative, None."""
    if isinstance(node, ast.Constant):
        return node.value is None or (isinstance(node.value, (int, float))
                                      and node.value <= 0)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return True  # -1 etc.
    return False


def _classify(call: ast.Call, bare: dict[str, str]) -> str | None:
    """Return the canonical constructor name ('queue.Queue',
    'collections.deque', ...) when ``call`` builds a queue, else None."""
    chain = dotted_name(call.func)
    if chain is None:
        return None
    if "." in chain:
        mod, _, name = chain.rpartition(".")
        if mod == "queue" and name in SIZED | UNSIZABLE:
            return f"queue.{name}"
        if mod == "collections" and name == "deque":
            return "collections.deque"
        return None
    return bare.get(chain)


def _bound_arg(call: ast.Call, canonical: str) -> ast.expr | None:
    """The expression supplying the bound, or None when absent."""
    if canonical == "collections.deque":
        for kw in call.keywords:
            if kw.arg == "maxlen":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
        return None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            return kw.value
    if call.args:
        return call.args[0]
    return None


class QueueDisciplinePass(AnalysisPass):
    id = "queue-discipline"
    description = ("unbounded queue.Queue()/deque() in sync|p2p|jobs|"
                   "pipeline (overload must shed, not buffer)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*SCOPED_DIRS):
            return
        bare = _bare_imports(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            canonical = _classify(node, bare)
            if canonical is None:
                continue
            if canonical in {f"queue.{n}" for n in UNSIZABLE}:
                yield ctx.finding(
                    node.lineno, self.id,
                    f"{canonical} has no capacity bound at all — use "
                    "queue.Queue(maxsize=N) so overload sheds instead of "
                    "buffering")
                continue
            bound = _bound_arg(node, canonical)
            if bound is None or _is_unbounded_literal(bound):
                kwarg = ("maxlen" if canonical == "collections.deque"
                         else "maxsize")
                yield ctx.finding(
                    node.lineno, self.id,
                    f"{canonical} constructed without an explicit {kwarg} "
                    "bound: an unbounded in-memory queue on an ingest/"
                    "dispatch path turns overload into memory growth — "
                    "bound it (or waive with a displacement argument)")
