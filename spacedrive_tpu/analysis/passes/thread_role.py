"""thread-role: provenance-driven role checks over the call graph.

Two checks, both powered by the thread-provenance lattice (the set of
root labels — event loop, job worker, pipeline stages, lane appliers,
serve-pool workers, supervisor, telemetry ticker — that can reach each
function along direct call edges):

**(a) event-loop-only functions must not block.** A sync function whose
provenance is exactly ``{event-loop}`` runs nowhere but on the loop —
typically a ``call_soon``/``add_done_callback`` callback or a
``create_task`` target. Blocking primitives in its body stall every
connected peer. Functions already covered by ``loop-blocking`` (those
reachable from an ``async def`` root in api|server|p2p) are excluded,
so each defect reports exactly once; what remains is the callback-only
surface neither async pass can see.

**(b) cross-class lockset round 2.** The per-class ``lockset`` pass
proves guarded-attr discipline but cannot tell WHICH threads run each
method. With provenance it can: an attribute mutated from >= 2 distinct
thread roots with no lock held in common across all mutation sites is a
data race no single-file view exposes (the two mutation sites may sit
in methods that per-file analysis has no reason to relate). Lock credit
at a site = locks lexically held in ``with`` blocks + the entry-lock
fixpoint for underscore-private helpers (every in-class caller holds L
=> the helper's body is credited with L — the ``_locked()`` idiom).
``__init__`` is exempt (construction happens-before publication), lock
attributes themselves are exempt, and only sites with non-empty
provenance count (a method no root reaches is dead or external API —
flagging it would be noise).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..callgraph import (EVENT_LOOP, CallGraph, ClassInfo, FunctionInfo,
                         ModuleInfo, blocking_call_reason, walk_own_body)
from ..engine import Finding, ProjectContext, ProjectPass
from .loop_blocking import _is_loop_async
from .lockset import MUTATOR_METHODS


def _classify(call: ast.Call, mi: ModuleInfo) -> str | None:
    return blocking_call_reason(call, mi, include_db=True,
                                include_open=False)


class _Site:
    """One ``self.X`` mutation site with its lock credit + provenance."""

    __slots__ = ("attr", "lineno", "locks", "roots", "method")

    def __init__(self, attr: str, lineno: int, locks: frozenset[str],
                 roots: frozenset[str], method: FunctionInfo) -> None:
        self.attr = attr
        self.lineno = lineno
        self.locks = locks
        self.roots = roots
        self.method = method


class ThreadRolePass(ProjectPass):
    id = "thread-role"
    description = ("event-loop-only functions must not block; attrs "
                   "mutated from >=2 thread roots need a common lock")

    def run_project(self, project: ProjectContext) -> Iterator[Finding]:
        graph = project.graph
        yield from self._check_loop_only(graph)
        yield from self._check_cross_root_attrs(graph)

    # -- (a) event-loop-only callbacks ---------------------------------------
    def _async_reach(self, graph: CallGraph) -> set[str]:
        """qnames reachable from any async-def root in api|server|p2p —
        loop-blocking's territory, excluded here."""
        from collections import deque

        seeds = [f for f in graph.functions.values() if _is_loop_async(f)]
        seen = {f.qname for f in seeds}
        queue = deque(seeds)
        while queue:
            fn = queue.popleft()
            for callee, _site, _txt in fn.calls:
                if callee.qname not in seen:
                    seen.add(callee.qname)
                    queue.append(callee)
        return seen

    def _check_loop_only(self, graph: CallGraph) -> Iterator[Finding]:
        async_reach = self._async_reach(graph)
        for fn in graph.functions.values():
            if fn.is_async or fn.qname in async_reach:
                continue
            if graph.provenance(fn) != frozenset({EVENT_LOOP}):
                continue
            mi = graph.modules.get(fn.modkey)
            if mi is None or mi.relpath != fn.relpath:
                continue
            for node in walk_own_body(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                reason = _classify(node, mi)
                if reason is None:
                    continue
                yield Finding(
                    str(mi.ctx.path), fn.relpath, node.lineno, self.id,
                    f"{fn.short} runs only on the event loop "
                    f"(provenance {{event-loop}}) but calls blocking "
                    f"{reason}")

    # -- (b) cross-root attribute mutations ----------------------------------
    def _check_cross_root_attrs(self, graph: CallGraph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            for ci in mi.classes:
                yield from self._check_class(ci, mi, graph)

    def _check_class(self, ci: ClassInfo, mi: ModuleInfo,
                     graph: CallGraph) -> Iterator[Finding]:
        if not ci.locks:
            return  # an unlocked class is plain lockset's problem space
        entry = self._entry_locks(ci)
        sites: dict[str, list[_Site]] = {}
        for name, method in ci.methods.items():
            if name == "__init__":
                continue
            roots = graph.provenance(method)
            if not roots:
                continue
            for attr, lineno, held in self._mutations(method, ci):
                if attr in ci.locks:
                    continue
                locks = frozenset(held) | entry.get(name, frozenset())
                sites.setdefault(attr, []).append(
                    _Site(attr, lineno, locks, roots, method))
        for attr in sorted(sites):
            group = sites[attr]
            all_roots = frozenset().union(*(s.roots for s in group))
            if len(all_roots) < 2:
                continue
            common = frozenset.intersection(*(s.locks for s in group))
            if common:
                continue
            first = min(group, key=lambda s: s.lineno)
            roots_txt = ", ".join(sorted(all_roots))
            methods_txt = ", ".join(sorted({s.method.name for s in group}))
            yield Finding(
                str(mi.ctx.path), ci.relpath, first.lineno, self.id,
                f"attr 'self.{attr}' of {ci.name} mutated from roots "
                f"{{{roots_txt}}} (in {methods_txt}) with no common lock")

    def _mutations(self, method: FunctionInfo, ci: ClassInfo,
                   ) -> Iterator[tuple[str, int, frozenset[str]]]:
        """(attr, lineno, locks-lexically-held) per self.X mutation."""
        for kind, payload, held in _walk_held(method.node.body,
                                              frozenset(), ci):
            if kind == "mut":
                attr, lineno = payload
                yield attr, lineno, held

    def _entry_locks(self, ci: ClassInfo) -> dict[str, frozenset[str]]:
        """Locks every in-class caller provably holds when calling each
        underscore-private helper — iterated to fixpoint so credit flows
        through helper chains (``_locked() -> _locked_inner()``)."""
        call_sites: dict[str, list[tuple[str, frozenset[str]]]] = {}
        for name, method in ci.methods.items():
            for kind, payload, held in _walk_held(method.node.body,
                                                  frozenset(), ci):
                if kind == "call":
                    call_sites.setdefault(payload, []).append((name, held))
        entry: dict[str, frozenset[str]] = {}
        for _ in range(len(ci.methods) + 1):
            changed = False
            for helper, sites in call_sites.items():
                if not helper.startswith("_") or helper == "__init__":
                    continue
                credit = frozenset.intersection(*(
                    held | entry.get(caller, frozenset())
                    for caller, held in sites))
                if entry.get(helper, frozenset()) != credit:
                    entry[helper] = credit
                    changed = True
            if not changed:
                break
        return entry


def _is_lock_item(expr: ast.expr, ci: ClassInfo) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and expr.attr in ci.locks)


def _stmt_mutations(stmt: ast.stmt) -> Iterator[tuple[str, int]]:
    """self.X writes in ONE simple statement."""
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        targets = []
    for t in targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, ast.Attribute) \
                    and isinstance(e.value, ast.Name) \
                    and e.value.id == "self":
                yield e.attr, stmt.lineno
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS \
                and isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id == "self":
            yield f.value.attr, stmt.lineno


_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _walk_held(stmts, held: frozenset[str], ci: ClassInfo,
               ) -> Iterator[tuple[str, object, frozenset[str]]]:
    """Walk a statement list tracking which of the class's locks are
    lexically held, yielding ``("mut", (attr, lineno), held)`` for each
    self.X mutation and ``("call", method-name, held)`` for each
    in-class ``self.m()`` call. Each node is visited exactly once with
    the correct lock set (a ``with`` nested inside an ``if`` credits
    its lock); nested defs/lambdas are deferred execution and skipped."""
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(stmt, ast.With):
            inner = held | {item.context_expr.attr for item in stmt.items
                            if _is_lock_item(item.context_expr, ci)}
            for item in stmt.items:  # lock exprs evaluate BEFORE acquire
                yield from _expr_events(item.context_expr, held, ci)
            yield from _walk_held(stmt.body, inner, ci)
            continue
        blocks = [getattr(stmt, f, None) for f in _BLOCK_FIELDS]
        blocks = [b for b in blocks if b]
        extra = [h.body for h in getattr(stmt, "handlers", ())] + \
                [c.body for c in getattr(stmt, "cases", ())]
        if blocks or extra:
            # compound statement: header expressions (If.test, For.iter,
            # While.test, Match.subject...) evaluate with the CURRENT set
            for field, value in ast.iter_fields(stmt):
                if field in _BLOCK_FIELDS + ("handlers", "cases"):
                    continue
                for node in (value if isinstance(value, list) else [value]):
                    if isinstance(node, ast.AST):
                        yield from _expr_events(node, held, ci)
            for block in blocks + extra:
                yield from _walk_held(block, held, ci)
        else:
            for attr, lineno in _stmt_mutations(stmt):
                yield "mut", (attr, lineno), held
            yield from _expr_events(stmt, held, ci)


def _expr_events(node: ast.AST, held: frozenset[str], ci: ClassInfo,
                 ) -> Iterator[tuple[str, object, frozenset[str]]]:
    """In-class self.m() calls inside one expression/simple statement."""
    from collections import deque

    queue = deque([node])
    while queue:
        cur = queue.popleft()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        if isinstance(cur, ast.Call) \
                and isinstance(cur.func, ast.Attribute) \
                and isinstance(cur.func.value, ast.Name) \
                and cur.func.value.id == "self" \
                and cur.func.attr in ci.methods:
            yield "call", cur.func.attr, held
        queue.extend(ast.iter_child_nodes(cur))
