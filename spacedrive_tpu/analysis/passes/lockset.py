"""lockset: interprocedural per-class race + self-deadlock detection.

The RacerD idea scoped to this codebase's one instance-locking idiom: a
class creates ``self._lock = threading.Lock()`` (or ``SdLock(...)``,
utils/locks.py) in ``__init__`` and guards its mutable ``self._x``
attributes with ``with self._lock:`` blocks. The ``lock-discipline``
pass covers the *module*-level ``_STATE`` twin of this shape; until
ISSUE 14 the ~47 instance locks had no checker at all — and the two
worst shipped concurrency bugs lived exactly there (the PR 8
``IngestBudget`` self-deadlock, the PR 12 merger races).

Per class, the pass:

1. collects its **locks**: ``self.X = Lock()/RLock()/SdLock()/SdRLock()/
   Condition()`` assignments anywhere in the class (Condition bundles an
   RLock; both R-forms are reentrant);
2. tracks, per method, the **lexically held** lock set at every
   statement: ``with self.X:`` holds X for the block;
   ``self.X.acquire(...)`` holds X for the rest of the function (the
   models/base try/finally idiom — deliberately credited past its
   ``release()``, trading false negatives for zero false positives);
   nested ``def``/``lambda`` bodies get NO credit (deferred execution);
3. propagates guard state through **intra-class helper calls** with the
   jax-wedge fixpoint: a method whose every ``self.helper()`` call site
   holds X is analyzed as entered-with-X-held (``_shed_locked``-style
   helpers); a method nobody in the class calls is an entry point and
   gets no credit;
4. infers the **guarded attribute set**: ``self._y`` is guarded by X
   when any method mutates it with X held (``__init__`` excluded —
   single-threaded construction);
5. flags every mutation (assignment, augmented/compound
   read-modify-write, subscript store/delete, mutating method call) of
   a guarded attribute at a point where NONE of its guarding locks is
   held — the classic lost-update window;
6. flags **re-acquisition of a non-reentrant lock already held on the
   same call path**: ``with self.X:`` (or ``.acquire()``) inside a
   lexical X-hold, or in a method reachable (ANY-call-site, transitive)
   from an X-hold — the exact PR 8 bug (``try_admit`` held the lock and
   called ``_shed``, which re-acquired it: silent self-deadlock), which
   no other pass can see.

Deliberate single-writer / GIL-atomic idioms (status counters bumped by
one owning thread, benign gauges) carry scoped waivers with a written
argument — see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

#: constructor leaves that make a ``self.X = <call>`` a lock attribute
LOCK_FACTORIES = {"Lock": False, "SdLock": False,
                  "RLock": True, "SdRLock": True, "Condition": True}

MUTATOR_METHODS = {
    "add", "append", "appendleft", "extend", "insert", "update",
    "setdefault", "pop", "popitem", "popleft", "clear", "remove",
    "discard",
}


class _Mutation:
    __slots__ = ("attr", "lineno", "held", "method", "rmw")

    def __init__(self, attr: str, lineno: int, held: frozenset[str],
                 method: "_Method", rmw: bool = False) -> None:
        self.attr = attr
        self.lineno = lineno
        self.held = held
        self.method = method
        #: compound read-modify-write (augmented assignment): not atomic
        #: even under the GIL, unlike a single dict/attr store
        self.rmw = rmw


class _Acquire:
    __slots__ = ("lock", "lineno", "held", "method")

    def __init__(self, lock: str, lineno: int, held: frozenset[str],
                 method: "_Method") -> None:
        self.lock = lock
        self.lineno = lineno
        self.held = held
        self.method = method


class _Call:
    __slots__ = ("callee", "lineno", "held", "method")

    def __init__(self, callee: str, lineno: int, held: frozenset[str],
                 method: "_Method") -> None:
        self.callee = callee
        self.lineno = lineno
        self.held = held
        self.method = method


class _Method:
    __slots__ = ("name", "node", "mutations", "acquires", "calls",
                 "entry_all", "entry_any")

    def __init__(self, name: str, node: ast.AST) -> None:
        self.name = name
        self.node = node
        self.mutations: list[_Mutation] = []
        self.acquires: list[_Acquire] = []
        self.calls: list[_Call] = []
        #: locks held at EVERY intra-class call site (guard credit)
        self.entry_all: frozenset[str] = frozenset()
        #: locks held at SOME intra-class call site (hazard propagation)
        self.entry_any: frozenset[str] = frozenset()


class _ClassInfo:
    def __init__(self, name: str) -> None:
        self.name = name
        #: lock attr name -> reentrant?
        self.locks: dict[str, bool] = {}
        self.methods: list[_Method] = []


class LocksetPass(AnalysisPass):
    id = "lockset"
    description = ("instance state mutated outside the lock that guards "
                   "it elsewhere, and non-reentrant self-lock "
                   "re-acquisition on one call path")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    # -- collection ----------------------------------------------------------
    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        info = _ClassInfo(cls.name)
        self._collect_locks(cls, info)
        if not info.locks:
            return
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                method = _Method(stmt.name, stmt)
                info.methods.append(method)
                self._scan_body(stmt.body, info, method, frozenset())
        self._propagate(info)
        yield from self._report(ctx, info)

    def _collect_locks(self, cls: ast.ClassDef, info: _ClassInfo) -> None:
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            leaf = (dotted_name(value.func) or "").split(".")[-1]
            if leaf not in LOCK_FACTORIES:
                continue
            for target in targets:
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self":
                    info.locks[target.attr] = LOCK_FACTORIES[leaf]

    def _self_attr(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _lock_in_expr(self, expr: ast.AST, info: _ClassInfo) -> str | None:
        attr = self._self_attr(expr)
        return attr if attr in info.locks else None

    def _scan(self, node: ast.AST, info: _ClassInfo, method: _Method,
              held: frozenset[str]) -> None:
        """Source-order walk of one method tracking the lexical hold set.
        ``held`` is immutable per recursion level; ``.acquire()`` credit
        extends to the remaining SIBLING statements via the return-value
        threading in _scan_body."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # deferred execution: the body runs when the lock state is
            # whatever the CALLER of the closure holds, not this scope's
            for child in ast.iter_child_nodes(node):
                self._scan(child, info, method, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                lock = self._lock_in_expr(item.context_expr, info)
                if lock is not None:
                    method.acquires.append(
                        _Acquire(lock, item.context_expr.lineno, held,
                                 method))
                    inner = inner | {lock}
                else:
                    # non-lock context managers may carry calls/mutations
                    self._scan(item.context_expr, info, method, inner)
            self._scan_body(node.body, info, method, inner)
            return
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                lock = self._lock_in_expr(func.value, info)
                if lock is not None and func.attr in ("acquire", "release"):
                    if func.attr == "acquire":
                        method.acquires.append(
                            _Acquire(lock, node.lineno, held, method))
                    # both fall through: no mutation/call bookkeeping
                    for arg in node.args:
                        self._scan(arg, info, method, held)
                    return
                callee_root = func.value
                if isinstance(callee_root, ast.Name) \
                        and callee_root.id == "self":
                    method.calls.append(
                        _Call(func.attr, node.lineno, held, method))
            mutation = self._mutation_in_call(node)
            if mutation is not None:
                method.mutations.append(
                    _Mutation(mutation, node.lineno, held, method))
            for child in ast.iter_child_nodes(node):
                self._scan(child, info, method, held)
            return
        mutated = self._mutation_in_stmt(node)
        for attr, lineno, rmw in mutated:
            method.mutations.append(
                _Mutation(attr, lineno, held, method, rmw=rmw))
        if hasattr(node, "body") and isinstance(getattr(node, "body"), list):
            # compound statements: walk each block with sibling threading
            for field in ("body", "orelse", "finalbody"):
                block = getattr(node, field, None)
                if isinstance(block, list) and block \
                        and isinstance(block[0], ast.stmt):
                    self._scan_body(block, info, method, held)
            for handler in getattr(node, "handlers", []):
                self._scan_body(handler.body, info, method, held)
            # non-statement children (test exprs, iterators, with items)
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
                    self._scan(child, info, method, held)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child, info, method, held)

    def _scan_body(self, body: list[ast.stmt], info: _ClassInfo,
                   method: _Method, held: frozenset[str]) -> None:
        """Statement list with `.acquire()` credit: an explicit acquire
        extends the hold set for the remaining statements of the block
        (and, via recursion, everything nested under them)."""
        for stmt in body:
            # the statement ITSELF is scanned with the pre-acquire set:
            # `if not X.acquire(False): X.acquire()` is ONE statement
            # whose two acquires are alternatives, not a re-acquisition
            self._scan(stmt, info, method, held)
            held = held | self._explicit_acquires(stmt, info)

    def _explicit_acquires(self, stmt: ast.stmt,
                           info: _ClassInfo) -> frozenset[str]:
        """Locks `.acquire()`d anywhere inside this statement — credited
        to the FOLLOWING siblings (the statement itself is scanned with
        the pre-acquire set, which is conservative for mutations that
        share a line with the acquire: none do in this tree)."""
        out: set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                lock = self._lock_in_expr(node.func.value, info)
                if lock is not None:
                    out.add(lock)
        return frozenset(out)

    # -- mutation classification --------------------------------------------
    def _mutation_in_stmt(self,
                          node: ast.AST) -> list[tuple[str, int, bool]]:
        out: list[tuple[str, int, bool]] = []

        def target_attr(t: ast.AST) -> str | None:
            attr = self._self_attr(t)
            if attr is not None:
                return attr
            if isinstance(t, ast.Subscript):
                return self._self_attr(t.value)
            return None

        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for sub in targets:
                    attr = target_attr(sub)
                    if attr is not None:
                        out.append((attr, node.lineno, False))
        elif isinstance(node, ast.AugAssign):
            attr = target_attr(node.target)
            if attr is not None:
                out.append((attr, node.lineno, True))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = target_attr(t)
                if attr is not None:
                    out.append((attr, node.lineno, False))
        return out

    def _mutation_in_call(self, call: ast.Call) -> str | None:
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in MUTATOR_METHODS:
            return self._self_attr(call.func.value)
        return None

    # -- interprocedural fixpoints -------------------------------------------
    def _propagate(self, info: _ClassInfo) -> None:
        """Two fixpoints over intra-class call sites. ``entry_all``
        (every call site holds X → guard credit) mirrors jax-wedge's
        helper rule; ``entry_any`` (some call site holds X → hazard
        reachability) powers the re-acquisition check."""
        by_name: dict[str, list[_Method]] = {}
        for m in info.methods:
            by_name.setdefault(m.name, []).append(m)
        sites: dict[str, list[_Call]] = {}
        for m in info.methods:
            for call in m.calls:
                if call.callee in by_name:
                    sites.setdefault(call.callee, []).append(call)

        changed = True
        while changed:
            changed = False
            for name, methods in by_name.items():
                call_sites = sites.get(name)
                if not call_sites:
                    continue  # entry point: no credit, no hazard inherit
                eff_all = frozenset.intersection(
                    *[c.held | c.method.entry_all for c in call_sites])
                eff_any = frozenset().union(
                    *[c.held | c.method.entry_any for c in call_sites])
                for m in methods:
                    if eff_all - m.entry_all:
                        m.entry_all = m.entry_all | eff_all
                        changed = True
                    if eff_any - m.entry_any:
                        m.entry_any = m.entry_any | eff_any
                        changed = True

    # -- reporting -----------------------------------------------------------
    def _report(self, ctx: FileContext,
                info: _ClassInfo) -> Iterator[Finding]:
        # guarded set: attr -> locks it was ever mutated under
        guarded: dict[str, set[str]] = {}
        for m in info.methods:
            if m.name == "__init__":
                continue
            for mut in m.mutations:
                for lock in mut.held | m.entry_all:
                    guarded.setdefault(mut.attr, set()).add(lock)
        # a lock attribute itself is never "state"
        for lock in info.locks:
            guarded.pop(lock, None)

        findings: list[tuple[int, Finding]] = []
        for m in info.methods:
            if m.name == "__init__":
                continue
            for mut in m.mutations:
                eff = mut.held | m.entry_all
                if mut.attr in guarded:
                    if not (guarded[mut.attr] & eff):
                        locks = "/".join(sorted(guarded[mut.attr]))
                        findings.append((mut.lineno, ctx.finding(
                            mut.lineno, self.id,
                            f"{info.name}.{mut.attr} is guarded by "
                            f"self.{locks} elsewhere but mutated here in "
                            f"'{m.name}' without it — lost-update race")))
                elif mut.rmw and not eff:
                    # never-guarded compound RMW in a lock-bearing class:
                    # += is read-then-write, NOT atomic under the GIL —
                    # two threads bumping it lose updates even though each
                    # single dict/attr store would be safe
                    findings.append((mut.lineno, ctx.finding(
                        mut.lineno, self.id,
                        f"{info.name}.{mut.attr}: compound "
                        f"read-modify-write in '{m.name}' outside any "
                        f"lock of a lock-bearing class — += is not "
                        f"GIL-atomic (lost updates across threads)")))
            for acq in m.acquires:
                if info.locks.get(acq.lock):
                    continue  # reentrant: re-acquisition is legal
                path = acq.held | m.entry_any
                if acq.lock in path:
                    findings.append((acq.lineno, ctx.finding(
                        acq.lineno, self.id,
                        f"{info.name}.'{m.name}' re-acquires non-reentrant "
                        f"self.{acq.lock} already held on this call path "
                        f"— guaranteed self-deadlock (the PR 8 "
                        f"IngestBudget shape)")))
        for _lineno, finding in sorted(findings, key=lambda p: p[0]):
            yield finding
