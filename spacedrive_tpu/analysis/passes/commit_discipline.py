"""commit-discipline: group commit only stays byte-identical if every
durable effect lives inside the commit stage's transaction scope.

The group committer (pipeline/executor.py) may run several
``pipeline_commit`` calls inside ONE outer transaction and roll them all
back together on a transient failure, restoring a shallow snapshot of the
checkpoint ``data``. That is only sound when:

- **every DB write in ``pipeline_commit`` happens inside a
  ``db.transaction()`` block** — a write outside it autocommits
  immediately and would SURVIVE the group rollback, leaving rows from a
  batch whose checkpoint cursor never advanced (re-committed on retry:
  duplicate CRDT ops, torn uniqueness);
- **the checkpoint ``data`` is only mutated by the commit stage** — a
  ``data[...] = ...`` from ``pipeline_page``/``pipeline_process`` runs on
  a speculative stage thread, so a pause would serialize state the
  committer never made durable (the page stage keeps its speculative
  cursor in ``scratch`` for exactly this reason).

Mechanics: inside any function named ``pipeline_commit`` (including
nested helpers defined within it), flag write-surface calls
(execute/executemany/insert/insert_ignore/insert_many/update/upsert/
delete on a DB-handle receiver — a name chain ending in ``db``) that are
not lexically inside a ``with <...>.transaction(...)`` block. Inside
``pipeline_page``/``pipeline_process``, flag subscript assignments to the
``data`` parameter and mutating calls on it (update/setdefault/pop/
popitem/clear). Reads are always fine.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name
from .pipeline_ordering import WRITE_ATTRS, _is_db_receiver

SPECULATIVE_STAGES = ("pipeline_page", "pipeline_process",
                      "pipeline_page_split", "pipeline_page_shard",
                      "pipeline_page_merge",
                      # the manifest stage halves (ISSUE 18): same
                      # speculative-thread contract
                      "pipeline_chunk_gather", "pipeline_chunk_process")

DATA_MUTATORS = {"update", "setdefault", "pop", "popitem", "clear"}


def _is_txn_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr == "transaction":
            return True
    return False


class CommitDisciplinePass(AnalysisPass):
    id = "commit-discipline"
    description = ("DB writes outside the commit stage's transaction scope, "
                   "or checkpoint-data mutation outside pipeline_commit "
                   "(group commit can only roll back what the txn owns)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name == "pipeline_commit":
                yield from self._check_commit(ctx, node)
            elif node.name in SPECULATIVE_STAGES:
                yield from self._check_speculative(ctx, node)

    # -- rule 1: commit writes must sit inside db.transaction() -------------
    def _check_commit(self, ctx: FileContext,
                      fn: ast.FunctionDef) -> Iterator[Finding]:
        def visit(node: ast.AST, in_txn: bool) -> Iterator[Finding]:
            for child in ast.iter_child_nodes(node):
                child_in_txn = in_txn
                if isinstance(child, ast.With) and _is_txn_with(child):
                    child_in_txn = True
                if not in_txn and isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute):
                    chain = dotted_name(child.func)
                    if chain is not None and child.func.attr in WRITE_ATTRS \
                            and _is_db_receiver(chain):
                        yield ctx.finding(
                            child.lineno, self.id,
                            f"DB write '{chain}()' outside the commit "
                            f"transaction scope — it would survive a "
                            f"group-commit rollback; move it inside "
                            f"'with db.transaction():'")
                yield from visit(child, child_in_txn)

        yield from visit(fn, False)

    # -- rule 2: speculative stages never touch the checkpoint data ---------
    def _check_speculative(self, ctx: FileContext,
                           fn: ast.FunctionDef) -> Iterator[Finding]:
        stage = fn.name.removeprefix("pipeline_")
        data_params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs)
                       if a.arg == "data"}
        if not data_params:
            return
        for node in ast.walk(fn):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "data":
                    yield ctx.finding(
                        node.lineno, self.id,
                        f"checkpoint 'data' mutated in pipeline {stage} "
                        f"stage — the cursor only advances in "
                        f"pipeline_commit (speculative state belongs in "
                        f"'scratch')")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "data" \
                    and node.func.attr in DATA_MUTATORS:
                yield ctx.finding(
                    node.lineno, self.id,
                    f"checkpoint 'data.{node.func.attr}()' in pipeline "
                    f"{stage} stage — the cursor only advances in "
                    f"pipeline_commit (speculative state belongs in "
                    f"'scratch')")
