"""swallowed-exception: job-pipeline code must not eat crashes silently.

In the job subsystems (jobs/, objects/, locations/) a broad handler
whose body is only ``pass``/``continue`` converts a crash into a report
that *looks* complete — the worker moves on, the step's work silently
never happened, and the wedge shows up later as unexplained missing
rows instead of an error the operator can act on. Rounds 4-5 showed
liveness bugs hide exactly here.

Flagged: ``except:``, ``except Exception:``, ``except BaseException:``
(alone or in a tuple) whose body contains nothing but ``pass`` or
``continue``, inside any function in the job-pipeline directories.
Handlers that log, set a fallback, append an error, or re-raise are
fine — so is a deliberate swallow waived with
``# lint: ok(swallowed-exception)`` and a comment saying why.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding

JOB_DIRS = ("jobs", "objects", "locations")

BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name):
        return handler.type.id in BROAD
    if isinstance(handler.type, ast.Tuple):
        return any(isinstance(elt, ast.Name) and elt.id in BROAD
                   for elt in handler.type.elts)
    return False


class SwallowedExceptionPass(AnalysisPass):
    id = "swallowed-exception"
    description = ("broad except handlers whose body is only "
                   "pass/continue in job-pipeline code")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dirs(*JOB_DIRS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if not all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in node.body):
                continue
            yield ctx.finding(
                node.lineno, self.id,
                "broad exception swallowed (body is only pass/continue) — "
                "a silent swallow turns a crash into a wedged or "
                "silently-incomplete job report; log it, narrow it, or "
                "waive with a reason")
