"""pipeline-ordering: DB writes in streaming-pipeline stages must go
through the committer.

The streaming executor (pipeline/executor.py) runs ``pipeline_page`` on the
prefetch thread and ``pipeline_process`` on the dispatch thread; only
``pipeline_commit`` runs on the job thread in strict batch order. A DB write
from a prefetch/dispatch callable would race the committer and break the
invariant the whole design rests on — commits (and the CRDT ops inside
them) are ordered exactly like the sequential step loop, so pause/resume
checkpoints and sync op-logs stay byte-identical.

Mechanics: inside any function named ``pipeline_page`` or
``pipeline_process`` (the executor's stage-naming convention, including
nested helpers defined within them), flag

- any ``.transaction(...)`` call — transactions belong to the committer;
- write-surface calls (execute/executemany/insert/insert_ignore/
  insert_many/update/upsert/delete) whose receiver is a DB handle (a name
  chain ending in ``db``), so dict ``.update()`` and friends don't trip it.

Reads (``db.query`` / ``db.find*``) are allowed anywhere — paging is the
prefetcher's whole job.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

STAGE_NAMES = ("pipeline_page", "pipeline_process",
               # the sharded-prefetch stages (ISSUE 17) run on the split
               # coordinator / gather shard / merger threads — same
               # read-only contract as pipeline_page
               "pipeline_page_split", "pipeline_page_shard",
               "pipeline_page_merge",
               # the manifest stage halves (ISSUE 18) ride the prefetch and
               # dispatch threads respectively — gather is read-only, the
               # chunk dispatch is compute-only; manifest writes go through
               # commit_manifest_rows inside pipeline_commit's transaction
               "pipeline_chunk_gather", "pipeline_chunk_process")

WRITE_ATTRS = {"execute", "executemany", "insert", "insert_ignore",
               "insert_many", "update", "upsert", "delete"}


def _is_db_receiver(chain: str) -> bool:
    """'db', 'self.db', 'ctx.library.db', … — the handle naming idiom."""
    head = chain.rsplit(".", 1)[0] if "." in chain else ""
    last = head.rsplit(".", 1)[-1] if head else ""
    return last == "db" or last == "database"


class PipelineOrderingPass(AnalysisPass):
    id = "pipeline-ordering"
    description = ("DB transactions/writes inside pipeline_page/"
                   "pipeline_process stages (commits belong to the "
                   "committer)")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in STAGE_NAMES:
                continue
            stage = node.name.removeprefix("pipeline_")
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) \
                        or not isinstance(call.func, ast.Attribute):
                    continue
                chain = dotted_name(call.func)
                if chain is None:
                    continue
                attr = call.func.attr
                if attr == "transaction":
                    yield ctx.finding(
                        call.lineno, self.id,
                        f"'{chain}()' in pipeline {stage} stage — "
                        f"transactions belong to pipeline_commit")
                elif attr in WRITE_ATTRS and _is_db_receiver(chain):
                    yield ctx.finding(
                        call.lineno, self.id,
                        f"DB write '{chain}()' in pipeline {stage} stage — "
                        f"route it through pipeline_commit")
