"""resource-leak: ``open()``/``socket.socket()`` results must be owned.

A leaked file handle is a slow failure (fd exhaustion after hours of
scanning); a leaked socket can hold a port. The rule: a resource
acquired in a function is fine when it is context-managed, ``.close()``d,
or its ownership visibly escapes the function. Everything else is a
leak on at least the exception path.

Escape forms accepted (conservative — this pass prefers silence over
false positives):
- ``with name:`` / ``with closing(name):`` context management;
- ``name.close()`` anywhere in the function (including finally blocks);
- ``return name`` / ``yield name`` (caller owns it now);
- ``name`` passed as an argument to any call (``os.fdopen(fd)``,
  ``loop.create_datagram_endpoint(sock=sock)`` — the callee owns it);
- ``name`` stored anywhere (``self._sock = name``, ``d[k] = name``) or
  aliased to another variable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisPass, FileContext, Finding, dotted_name

ACQUIRERS = {"open": "open", "socket.socket": "socket.socket",
             "io.open": "io.open"}


class ResourceLeakPass(AnalysisPass):
    id = "resource-leak"
    description = ("open()/socket.socket() results neither context-managed "
                   "nor closed nor escaping")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(ctx, node)

    def _acquisitions(self, func: ast.AST) -> list[tuple[str, int, str]]:
        """(var name, lineno, what) for resource-constructor assignments in
        this function's own body (nested defs get their own scan)."""
        out: list[tuple[str, int, str]] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                d = dotted_name(node.value.func)
                if d in ACQUIRERS:
                    out.append((node.targets[0].id, node.lineno,
                                ACQUIRERS[d]))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in getattr(func, "body", []):
            visit(stmt)
        return out

    def _scan(self, ctx: FileContext, func: ast.AST) -> Iterator[Finding]:
        acquired = self._acquisitions(func)
        if not acquired:
            return
        for name, lineno, what in acquired:
            if not self._owned(func, name):
                yield ctx.finding(
                    lineno, self.id,
                    f"'{name}' from {what}() is neither context-managed, "
                    f"closed, nor handed off in '{getattr(func, 'name', '?')}'"
                    " — a raise before close() leaks the descriptor")

    def _owned(self, func: ast.AST, name: str) -> bool:
        """True when the resource is context-managed, closed, or escapes.
        Scans the WHOLE function subtree including nested defs: a closure
        closing over the resource may be its legitimate closer."""

        # one parent map for the whole function; every direct_ref probe
        # below shares it instead of re-walking its subtree
        parents: dict[ast.AST, ast.AST] = {}
        for outer in ast.walk(func):
            for child in ast.iter_child_nodes(outer):
                parents[child] = outer

        def direct_ref(node: ast.AST) -> bool:
            """A bare reference to ``name`` inside ``node`` — one whose
            value is handed somewhere, NOT an attribute/method use on it
            (``fh.read()`` consumes the handle; it doesn't transfer it)."""
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id == name \
                        and not isinstance(parents.get(sub), ast.Attribute):
                    return True
            return False

        for node in ast.walk(func):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(direct_ref(item.context_expr) for item in node.items):
                    return True
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "close" \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == name:
                    return True
                if any(direct_ref(arg) for arg in node.args) \
                        or any(direct_ref(kw.value) for kw in node.keywords):
                    return True
            elif isinstance(node, (ast.Return, ast.Yield)):
                if node.value is not None and direct_ref(node.value):
                    return True
            elif isinstance(node, ast.Assign):
                # aliasing or storing (self.x = name, d[k] = name, y = name)
                if direct_ref(node.value) and not (
                        isinstance(node.value, ast.Call)
                        and dotted_name(node.value.func) in ACQUIRERS):
                    return True
        return False
