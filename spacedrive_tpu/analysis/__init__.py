"""sdlint: the multi-pass static-analysis framework gating this tree.

Public surface:
- :func:`spacedrive_tpu.analysis.engine.main` — the CLI
  (``python -m spacedrive_tpu.analysis``);
- :class:`PassManager` / :class:`FileContext` / :class:`AnalysisPass` /
  :class:`Finding` — the framework, for tests and new passes;
- :class:`ProjectContext` / :class:`ProjectPass` and
  :func:`build_graph` — the whole-program layer (ISSUE 16): the
  project call graph, thread-provenance lattice, and the base class
  for passes that consume them;
- the baseline ratchet helpers (:func:`load_baseline`, :func:`ratchet`,
  :func:`save_baseline`).

See docs/static-analysis.md for the pass list, waiver syntax, and the
baseline workflow.
"""

from .callgraph import build_graph
from .engine import (AnalysisPass, FileContext, Finding, PassManager,
                     ProjectContext, ProjectPass, build_manager,
                     default_baseline_path, default_root, load_baseline,
                     main, ratchet, save_baseline)
from .passes import REGISTRY, all_passes

__all__ = [
    "AnalysisPass", "FileContext", "Finding", "PassManager",
    "ProjectContext", "ProjectPass", "build_graph",
    "build_manager", "default_baseline_path", "default_root",
    "load_baseline", "main", "ratchet", "save_baseline",
    "REGISTRY", "all_passes",
]
