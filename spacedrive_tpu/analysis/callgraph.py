"""Whole-program call graph + thread-provenance lattice for sdlint.

Until ISSUE 16 every pass was file- or class-local: the ``lockset``
pass could not see a ``db.query()`` buried two modules below a
``with self._lock:`` body, and ``async-blocking`` could not see a sync
``socket.recv`` reached through a helper in another module. As the
serve tier goes distributed, the dominant tail-latency and deadlock
risks are exactly those cross-module shapes — blocking I/O while
holding a named lock, event-loop stalls reached interprocedurally —
which only a project-wide analysis catches before a soak does.

This module is the shared substrate the whole-program passes
(``hold-blocking``, ``loop-blocking``, ``thread-role``) stand on:

- :class:`ProjectContext` — every :class:`FileContext` of a scan,
  parsed once by the engine, plus the lazily-built graph;
- :class:`CallGraph` — one :class:`FunctionInfo` per ``def``/
  ``async def``/spawned ``lambda`` with **resolved call edges**:
  module import resolution (absolute, relative, aliased, and
  re-exported names through ``__init__`` chains), class-method binding
  through ``self``/``cls`` (including base classes and one-level
  ``self._x = Ctor()`` attribute types), local ``x = Ctor()``
  inference, dict-of-callables dispatch tables, decorator-transparent
  name binding, and ``functools.partial`` unwrapping;
- **thread roots** — the places concurrency is born:
  ``threading.Thread(target=...)`` (label = the literal ``name=`` role
  when present), ``executor.submit/map``, ``loop.run_in_executor``,
  ``_thread.start_new_thread``, ``asyncio.create_task`` /
  ``call_soon[_threadsafe]`` / ``call_later``/``call_at`` /
  ``add_done_callback`` (all ``event-loop``), every ``async def`` in
  the event-loop subsystems (api/ server/ p2p/ — one shared
  ``event-loop`` label: the loop is ONE thread), the pipeline stage
  convention (``pipeline_page``/``pipeline_process`` run on the
  prefetch/dispatch threads; ``pipeline_commit`` and ``execute_step``
  on the job worker);
- the **provenance lattice**: every function carries the set of root
  labels that can reach it along *direct* call edges (spawn edges
  start a NEW root — the spawner's provenance does not leak into the
  target). ``provenance(f) == {"event-loop"}`` is the load-bearing
  fact the ``thread-role`` pass keys on;
- the shared **blocking-call classifier** (sleep/socket/subprocess/
  requests/file-I/O/db.query-class/unbounded joins), import-alias
  aware so ``from time import sleep as snooze`` still classifies;
- reverse reachability over the SCC condensation for ``--changed``:
  a change inside a callee can create or kill a finding anchored at
  any transitive caller, so the impacted set is the changed functions
  plus everything that can reach them (cycles ride along whole).

Soundness posture: name resolution is best-effort and *under*-
approximate (an unresolvable dynamic call contributes no edge), while
the blocking classifier is *over*-approximate at the call site — so a
witness path is always a real chain of source-level calls, and the
deliberate escape hatches (``run_in_executor`` targets, spawned
threads) never launder provenance.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterable, Iterator

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (engine imports us)
    from .engine import FileContext

from .engine import dotted_name

#: subsystems whose ``async def``s run on the node's asyncio loops
EVENT_LOOP_DIRS = ("api", "server", "p2p")

#: the one label every event-loop root shares — the loop is a single
#: thread, so two async handlers are NOT two concurrent roots
EVENT_LOOP = "event-loop"

#: stage-name convention → root label (pipeline/executor.py threads;
#: pipeline_commit and execute_step run on the job-worker thread)
STAGE_ROOTS = {
    "pipeline_page": "pipeline.page",
    "pipeline_process": "pipeline.process",
    "pipeline_commit": "job-worker",
    "execute_step": "job-worker",
    # sharded prefetch (ISSUE 17): split coordinator, gather shard
    # workers (several concurrent threads share one root label — the
    # cross-root attr check still sees them as distinct from every other
    # root), and the ordered merger
    "pipeline_page_split": "pipeline.page",
    "pipeline_page_shard": "pipeline.gather",
    "pipeline_page_merge": "pipeline.merge",
}

#: fully-qualified external calls that block the calling thread
BLOCKING_EXT = {
    "time.sleep": "sleep",
    "socket.create_connection": "socket",
    "socket.getaddrinfo": "socket",
    "socket.gethostbyname": "socket",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "os.system": "subprocess",
    "shutil.copy": "file-io",
    "shutil.copy2": "file-io",
    "shutil.copytree": "file-io",
    "shutil.move": "file-io",
    "shutil.rmtree": "file-io",
    "urllib.request.urlopen": "network",
}

#: attribute methods that block regardless of receiver resolution
BLOCKING_METHODS = {
    "read_bytes": "file-io", "read_text": "file-io",
    "write_bytes": "file-io", "write_text": "file-io",
    "recv": "socket", "recv_into": "socket", "accept": "socket",
    "sendall": "socket",
}

#: zero-argument waits that can park the thread forever
UNBOUNDED_METHODS = ("result", "join")

#: the DB surface (models/base.Database) — every one of these takes the
#: writer or reader lock and runs SQLite I/O
DB_METHODS = {
    "query", "transaction", "execute", "executemany", "execute_noted",
    "executemany_noted", "insert", "insert_ignore", "insert_many",
    "update", "upsert", "delete",
}

#: lock factories a ``with`` item can hold (threading + utils/locks)
LOCK_FACTORIES = {"Lock": False, "SdLock": False,
                  "RLock": True, "SdRLock": True, "Condition": True}


def is_db_receiver(chain: str) -> bool:
    """'db.query', 'ctx.library.db.update', 'self._db.execute' — the
    handle-naming idiom shared with the pipeline-ordering pass."""
    head = chain.rsplit(".", 1)[0] if "." in chain else ""
    last = head.rsplit(".", 1)[-1].lstrip("_") if head else ""
    return last in ("db", "database")


class FunctionInfo:
    """One ``def``/``async def``/spawned ``lambda`` in the project."""

    __slots__ = ("qname", "relpath", "modkey", "name", "cls", "node",
                 "is_async", "lineno", "calls", "local_names", "parent")

    def __init__(self, qname: str, relpath: str, modkey: str, name: str,
                 cls: "ClassInfo | None", node: ast.AST,
                 parent: "FunctionInfo | None" = None) -> None:
        self.qname = qname
        self.relpath = relpath
        self.modkey = modkey
        self.name = name
        self.cls = cls
        self.node = node
        self.parent = parent
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.lineno = getattr(node, "lineno", 0)
        #: resolved direct-call edges: (callee, call-site node, rendering)
        self.calls: list[tuple["FunctionInfo", ast.Call, str]] = []
        #: names bound to nested defs inside this function
        self.local_names: dict[str, "FunctionInfo"] = {}

    @property
    def short(self) -> str:
        """'lanes.IngestLanes._apply' — the witness-path rendering (no
        line numbers: witness text is part of the baseline key)."""
        stem = self.relpath.rsplit("/", 1)[-1].removesuffix(".py")
        return f"{stem}.{self.qname.split('::', 1)[1]}"

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<fn {self.qname}>"


class ClassInfo:
    __slots__ = ("name", "modkey", "relpath", "node", "bases", "methods",
                 "attr_types", "locks")

    def __init__(self, name: str, modkey: str, relpath: str,
                 node: ast.ClassDef) -> None:
        self.name = name
        self.modkey = modkey
        self.relpath = relpath
        self.node = node
        self.bases: list[ast.expr] = list(node.bases)
        self.methods: dict[str, FunctionInfo] = {}
        #: ``self.x = Ctor()`` one-level attribute types: attr -> ClassInfo
        self.attr_types: dict[str, "ClassInfo"] = {}
        #: lock attrs assigned anywhere in the class: attr -> reentrant?
        self.locks: dict[str, bool] = {}


class ModuleInfo:
    __slots__ = ("modkey", "relpath", "ctx", "defs", "classes", "bindings",
                 "dispatch")

    def __init__(self, modkey: str, relpath: str, ctx: "FileContext") -> None:
        self.modkey = modkey
        self.relpath = relpath
        self.ctx = ctx
        #: top-level name -> FunctionInfo | ClassInfo
        self.defs: dict[str, object] = {}
        self.classes: list[ClassInfo] = []
        #: imported name -> ("module", key) | ("name", key, orig) |
        #: ("ext", dotted)
        self.bindings: dict[str, tuple] = {}
        #: module-level dict-of-callables tables: name -> value exprs
        self.dispatch: dict[str, list[ast.expr]] = {}


class Root:
    """One place concurrency is born: a label plus the entry function."""

    __slots__ = ("label", "kind", "fn", "lineno", "site_relpath")

    def __init__(self, label: str, kind: str, fn: FunctionInfo,
                 lineno: int, site_relpath: str) -> None:
        self.label = label
        self.kind = kind
        self.fn = fn
        self.lineno = lineno
        self.site_relpath = site_relpath


class CallGraph:
    """The resolved project graph. Build with :func:`build_graph`."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.roots: list[Root] = []
        self._provenance: dict[str, frozenset[str]] | None = None
        self._callers: dict[str, list[FunctionInfo]] | None = None

    # -- queries -------------------------------------------------------------
    def provenance(self, fn: FunctionInfo) -> frozenset[str]:
        """Root labels that can reach ``fn`` along direct call edges."""
        if self._provenance is None:
            self._provenance = self._compute_provenance()
        return self._provenance.get(fn.qname, frozenset())

    def callers_of(self, fn: FunctionInfo) -> list[FunctionInfo]:
        if self._callers is None:
            rev: dict[str, list[FunctionInfo]] = {}
            for f in self.functions.values():
                for callee, _site, _txt in f.calls:
                    rev.setdefault(callee.qname, []).append(f)
            self._callers = rev
        return self._callers.get(fn.qname, [])

    def functions_in(self, relpath: str) -> Iterator[FunctionInfo]:
        for f in self.functions.values():
            if f.relpath == relpath:
                yield f

    def impacted_files(self, changed: Iterable[str]) -> set[str]:
        """Files owning a function that can REACH a function defined in
        a changed file (reverse reachability over the condensation: a
        callee edit can create or kill a finding anchored at any
        transitive caller; members of a cycle ride along whole)."""
        changed_set = set(changed)
        seeds = [f for f in self.functions.values()
                 if f.relpath in changed_set]
        seen: set[str] = {f.qname for f in seeds}
        stack = list(seeds)
        out = set(changed_set)
        while stack:
            fn = stack.pop()
            out.add(fn.relpath)
            for caller in self.callers_of(fn):
                if caller.qname not in seen:
                    seen.add(caller.qname)
                    stack.append(caller)
        return out

    def reachable_blocking(self, fn: FunctionInfo,
                           classify, max_depth: int = 12,
                           skip_holder=None,
                           ) -> "tuple[list[FunctionInfo], int, str] | None":
        """Shortest chain ``[fn, …, holder-of-blocking-call]`` plus the
        blocking call's line and rendered reason, or None. ``classify``
        maps an ``(ast.Call, ModuleInfo)`` pair to a reason string or
        None — passes plug their own blocking vocabulary in.
        ``skip_holder(fn)`` exempts a function's OWN body from
        classification (another pass's domain) while still descending
        through its callees."""
        from collections import deque

        queue: deque[tuple[FunctionInfo, tuple[FunctionInfo, ...]]] = \
            deque([(fn, (fn,))])
        seen = {fn.qname}
        while queue:
            cur, path = queue.popleft()
            mi = self.modules.get(cur.modkey)
            if mi is not None and not (skip_holder is not None
                                       and skip_holder(cur)):
                hit = first_blocking_call(cur, mi, classify)
                if hit is not None:
                    return list(path), hit[0], hit[1]
            if len(path) > max_depth:
                continue
            for callee, _site, _txt in cur.calls:
                if callee.qname not in seen:
                    seen.add(callee.qname)
                    queue.append((callee, path + (callee,)))
        return None

    # -- provenance ----------------------------------------------------------
    def _compute_provenance(self) -> dict[str, frozenset[str]]:
        prov: dict[str, set[str]] = {}
        from collections import deque

        queue: deque[FunctionInfo] = deque()
        for root in self.roots:
            labels = prov.setdefault(root.fn.qname, set())
            if root.label not in labels:
                labels.add(root.label)
                queue.append(root.fn)
        while queue:
            fn = queue.popleft()
            labels = prov.get(fn.qname, set())
            for callee, _site, _txt in fn.calls:
                tgt = prov.setdefault(callee.qname, set())
                if labels - tgt:
                    tgt |= labels
                    queue.append(callee)
        return {q: frozenset(s) for q, s in prov.items()}


def first_blocking_call(fn: FunctionInfo, mi: ModuleInfo,
                        classify) -> tuple[int, str] | None:
    """Earliest call in ``fn``'s own body that ``classify`` marks
    blocking. Nested defs/lambdas are deferred execution — skipped
    (they are their own FunctionInfos when spawned)."""
    best: tuple[int, str] | None = None
    for node in walk_own_body(fn.node):
        if isinstance(node, ast.Call):
            reason = classify(node, mi)
            if reason is not None \
                    and (best is None or node.lineno < best[0]):
                best = (node.lineno, reason)
    return best


def walk_own_body(func: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` scoped to one function: does not descend into nested
    ``def``/``async def``/``lambda`` bodies."""
    from collections import deque

    queue = deque(ast.iter_child_nodes(func))
    while queue:
        node = queue.popleft()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        queue.extend(ast.iter_child_nodes(node))


def canonical_dotted(call: ast.Call, mi: ModuleInfo) -> str | None:
    """The dotted call target with its root de-aliased through the
    module's import bindings: ``snooze()`` after ``from time import
    sleep as snooze`` canonicalizes to ``time.sleep``."""
    chain = dotted_name(call.func)
    if chain is None:
        return None
    root, _, rest = chain.partition(".")
    binding = mi.bindings.get(root)
    if binding is None:
        return chain
    if binding[0] == "ext":
        return binding[1] + ("." + rest if rest else "")
    if binding[0] == "ext-name":
        return binding[1] + ("." + rest if rest else "")
    return chain


def blocking_call_reason(call: ast.Call, mi: ModuleInfo, *,
                         include_db: bool = True,
                         include_open: bool = False) -> str | None:
    """The shared blocking classifier. Returns a short rendered reason
    ("time.sleep()", "db write '….update()'") or None. ``include_db``
    adds the models/base query/transaction surface; ``include_open``
    adds bare ``open()`` (wanted under a lock, too noisy on a loop
    where async-blocking's narrower file-I/O set already gates)."""
    dotted = canonical_dotted(call, mi)
    if dotted is not None:
        if dotted in BLOCKING_EXT:
            return f"{dotted}()"
        if dotted.split(".")[0] == "requests":
            return f"{dotted}() (requests is synchronous)"
        if include_open and dotted == "open":
            return "open()"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        chain = dotted_name(call.func) or f"?.{attr}"
        if include_db and attr in DB_METHODS and is_db_receiver(chain):
            return f"DB call '{chain}()'"
        if attr in BLOCKING_METHODS:
            return f".{attr}()"
        if attr in UNBOUNDED_METHODS and not call.args \
                and not call.keywords:
            return f"unbounded .{attr}()"
    return None


def witness(path: list[FunctionInfo]) -> str:
    """'a.f -> b.g -> c.h' — deterministic (no line numbers: this text
    lands in baseline keys)."""
    return " -> ".join(f.short for f in path)


# -- graph construction -------------------------------------------------------

def modkey_for(relpath: str) -> str:
    """'sync/lanes.py' -> 'sync.lanes'; 'sync/__init__.py' -> 'sync';
    'library.py' -> 'library'."""
    parts = relpath.removesuffix(".py").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or ""


class _Builder:
    """Three phases over the parsed project: collect definitions, wire
    imports, then resolve call/spawn sites per function."""

    def __init__(self, files: dict[str, "FileContext"],
                 root_names: tuple[str, ...]) -> None:
        self.graph = CallGraph()
        self.files = files
        #: leading components stripped from absolute imports: the scan
        #: root's own directory name plus the installed package name
        self.root_names = root_names

    def build(self) -> CallGraph:
        for relpath, ctx in sorted(self.files.items()):
            self._collect_module(relpath, ctx)
        for mi in self.graph.modules.values():
            self._collect_imports(mi)
        for mi in self.graph.modules.values():
            self._resolve_attr_types(mi)
        for mi in self.graph.modules.values():
            for fn in list(self._module_functions(mi)):
                self._resolve_body(fn, mi)
        self._seed_convention_roots()
        return self.graph

    # -- phase 1: definitions ------------------------------------------------
    def _collect_module(self, relpath: str, ctx: "FileContext") -> None:
        mi = ModuleInfo(modkey_for(relpath), relpath, ctx)
        self.graph.modules[mi.modkey] = mi
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._new_function(stmt.name, relpath, mi.modkey,
                                        None, stmt)
                mi.defs[stmt.name] = fn
                self._collect_nested(fn, stmt, relpath, mi.modkey)
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(stmt.name, mi.modkey, relpath, stmt)
                mi.defs[stmt.name] = ci
                mi.classes.append(ci)
                self._collect_class(ci, stmt, relpath, mi.modkey)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Dict):
                values = [v for v in stmt.value.values if v is not None]
                if values and all(
                        isinstance(v, (ast.Name, ast.Attribute, ast.Lambda))
                        for v in values):
                    mi.dispatch[stmt.targets[0].id] = values

    def _collect_class(self, ci: ClassInfo, cls: ast.ClassDef,
                       relpath: str, modkey: str) -> None:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._new_function(f"{ci.name}.{stmt.name}", relpath,
                                        modkey, ci, stmt)
                ci.methods[stmt.name] = fn
                self._collect_nested(fn, stmt, relpath, modkey)
        # lock attrs: ``self.X = Lock()/SdLock(...)…`` anywhere in the class
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if not isinstance(node.value, ast.Call):
                continue
            factory = dotted_name(node.value.func) or ""
            if factory.split(".")[0] == "asyncio":
                continue  # asyncio.Lock guards await interleave, not threads
            leaf = factory.split(".")[-1]
            if leaf not in LOCK_FACTORIES:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    ci.locks[t.attr] = LOCK_FACTORIES[leaf]

    def _collect_nested(self, parent: FunctionInfo, func: ast.AST,
                        relpath: str, modkey: str) -> None:
        """Nested defs become their own FunctionInfos, name-bound in the
        parent so `def _work(): …; Thread(target=_work)` resolves."""
        for node in walk_own_body(func):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._new_function(
                    f"{parent.qname.split('::', 1)[1]}.<locals>.{node.name}",
                    relpath, modkey, parent.cls, node, parent)
                parent.local_names[node.name] = fn
                self._collect_nested(fn, node, relpath, modkey)

    def _new_function(self, qualpath: str, relpath: str, modkey: str,
                      cls: ClassInfo | None, node: ast.AST,
                      parent: FunctionInfo | None = None) -> FunctionInfo:
        name = qualpath.rsplit(".", 1)[-1]
        fn = FunctionInfo(f"{relpath}::{qualpath}", relpath, modkey, name,
                          cls, node, parent)
        self.graph.functions[fn.qname] = fn
        return fn

    # -- phase 2: imports ----------------------------------------------------
    def _project_modkey(self, dotted: str) -> str | None:
        """Map an absolute import path onto a scanned module key, or a
        package that contains scanned modules."""
        candidates = [dotted]
        first, _, rest = dotted.partition(".")
        if first in self.root_names and rest:
            candidates.append(rest)
        for cand in candidates:
            if cand in self.graph.modules:
                return cand
            prefix = cand + "."
            if any(k.startswith(prefix) for k in self.graph.modules):
                return cand
        return None

    def _collect_imports(self, mi: ModuleInfo) -> None:
        # the containing package: for 'sync/ingest.py' AND for the
        # package module 'sync/__init__.py' itself this is ['sync'],
        # which is exactly what a level-1 relative import resolves from
        pkg_parts = mi.relpath.split("/")[:-1]
        for stmt in ast.walk(mi.ctx.tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    target = (alias.name if alias.asname
                              else alias.name.split(".")[0])
                    key = self._project_modkey(target)
                    mi.bindings[bound] = (("module", key) if key is not None
                                          else ("ext", target))
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    base = pkg_parts[:len(pkg_parts) - (stmt.level - 1)] \
                        if stmt.level > 1 else pkg_parts
                    if stmt.level - 1 > len(pkg_parts):
                        continue  # escapes the scan root
                    src = ".".join(base + (stmt.module or "").split(".")) \
                        if stmt.module else ".".join(base)
                    src = src.strip(".")
                    key = src if src in self.graph.modules \
                        else self._project_modkey(src) if src else None
                else:
                    key = self._project_modkey(stmt.module or "")
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    if key is not None:
                        sub = f"{key}.{alias.name}"
                        if sub in self.graph.modules:
                            mi.bindings[bound] = ("module", sub)
                        else:
                            mi.bindings[bound] = ("name", key, alias.name)
                    elif not stmt.level:
                        mi.bindings[bound] = \
                            ("ext-name", f"{stmt.module}.{alias.name}")

    def _resolve_global(self, modkey: str, name: str,
                        _depth: int = 0) -> object | None:
        """FunctionInfo/ClassInfo for ``name`` as seen from ``modkey``,
        following re-export chains (``from .lanes import X`` in
        ``sync/__init__.py``) to a bounded depth."""
        if _depth > 8:
            return None
        mi = self.graph.modules.get(modkey)
        if mi is None:
            return None
        if name in mi.defs:
            return mi.defs[name]
        binding = mi.bindings.get(name)
        if binding is None:
            return None
        if binding[0] == "module":
            return ("module", binding[1])
        if binding[0] == "name":
            return self._resolve_global(binding[1], binding[2], _depth + 1)
        return None

    # -- phase 2.5: one-level attribute types --------------------------------
    def _resolve_attr_types(self, mi: ModuleInfo) -> None:
        for ci in mi.classes:
            for method in ci.methods.values():
                for node in walk_own_body(method.node):
                    if not isinstance(node, ast.Assign) \
                            or not isinstance(node.value, ast.Call):
                        continue
                    target_ci = self._resolve_ctor(node.value.func, mi)
                    if target_ci is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            ci.attr_types[t.attr] = target_ci

    def _resolve_ctor(self, func: ast.expr,
                      mi: ModuleInfo) -> ClassInfo | None:
        obj = self._resolve_callable_expr(func, mi, None, None)
        return obj if isinstance(obj, ClassInfo) else None

    # -- phase 3: call sites -------------------------------------------------
    def _module_functions(self, mi: ModuleInfo) -> Iterator[FunctionInfo]:
        for fn in self.graph.functions.values():
            if fn.modkey == mi.modkey and fn.relpath == mi.relpath:
                yield fn

    def _resolve_body(self, fn: FunctionInfo, mi: ModuleInfo) -> None:
        local_types: dict[str, ClassInfo] = {}
        # one-level local inference: ``x = Ctor(...); x.m()``
        for node in walk_own_body(fn.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                ci = self._resolve_ctor(node.value.func, mi)
                if ci is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_types[t.id] = ci
        lambda_seq = 0
        # a spawn target that is itself a Call node (``partial(f, x)``,
        # ``create_task(self._serve())``) must NOT also resolve as a
        # direct call edge — that would leak the spawner's provenance
        # into the spawned body (walk order is outer-before-inner, so
        # the mark lands before the inner node is visited)
        consumed: set[int] = set()
        for node in walk_own_body(fn.node):
            if not isinstance(node, ast.Call) or id(node) in consumed:
                continue
            spawn = self._spawn_site(node, fn, mi, local_types)
            if spawn is not None:
                kind, target_expr, label_hint = spawn
                if isinstance(target_expr, ast.Call):
                    consumed.add(id(target_expr))
                lambda_seq = self._register_root(
                    kind, target_expr, label_hint, node, fn, mi,
                    local_types, lambda_seq)
                continue
            for target in self._call_targets(node, fn, mi, local_types):
                fn.calls.append((target, node,
                                 dotted_name(node.func) or target.name))

    def _call_targets(self, call: ast.Call, fn: FunctionInfo,
                      mi: ModuleInfo,
                      local_types: dict[str, ClassInfo],
                      ) -> list[FunctionInfo]:
        func = call.func
        # dict-of-callables: TABLE[key](...) fans out to every value
        if isinstance(func, ast.Subscript) \
                and isinstance(func.value, ast.Name) \
                and func.value.id in mi.dispatch:
            out = []
            for expr in mi.dispatch[func.value.id]:
                if isinstance(expr, ast.Lambda):
                    continue  # table lambdas: no named body to bind
                tgt = self._resolve_callable_expr(expr, mi, fn, local_types)
                out.extend(self._as_functions(tgt, call))
            return out
        tgt = self._resolve_callable_expr(func, mi, fn, local_types)
        out = self._as_functions(tgt, call)
        # functools.partial(f, ...) used INLINE: partial(f)() — and, far
        # more commonly, partial as an argument to a known wrapper is
        # handled at spawn sites; a bare partial(...) call contributes
        # the wrapped callable's edge so later invocation is covered
        dotted = canonical_dotted(call, mi)
        if dotted in ("functools.partial", "partial") and call.args:
            inner = self._resolve_callable_expr(call.args[0], mi, fn,
                                                local_types)
            out.extend(self._as_functions(inner, call))
        return out

    def _as_functions(self, obj: object,
                      call: ast.Call) -> list[FunctionInfo]:
        if isinstance(obj, FunctionInfo):
            return [obj]
        if isinstance(obj, ClassInfo):
            init = self._lookup_method(obj, "__init__")
            return [init] if init is not None else []
        return []

    def _lookup_method(self, ci: ClassInfo,
                       name: str, _depth: int = 0) -> FunctionInfo | None:
        if name in ci.methods:
            return ci.methods[name]
        if _depth > 8:
            return None
        mi = self.graph.modules.get(ci.modkey)
        for base in ci.bases:
            resolved = None
            if mi is not None:
                resolved = self._resolve_callable_expr(base, mi, None, None)
            if isinstance(resolved, ClassInfo):
                found = self._lookup_method(resolved, name, _depth + 1)
                if found is not None:
                    return found
        return None

    def _resolve_callable_expr(self, expr: ast.expr, mi: ModuleInfo,
                               fn: FunctionInfo | None,
                               local_types: dict[str, ClassInfo] | None,
                               ) -> object | None:
        """FunctionInfo/ClassInfo for a callable expression, or None."""
        if isinstance(expr, ast.Name):
            name = expr.id
            cur: FunctionInfo | None = fn
            while cur is not None:  # the lexical def chain, innermost out
                if name in cur.local_names:
                    return cur.local_names[name]
                cur = cur.parent
            return _plain(self._resolve_global(mi.modkey, name))
        if isinstance(expr, ast.Attribute):
            parts = []
            node: ast.expr = expr
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            if not isinstance(node, ast.Name):
                return None
            parts.append(node.id)
            parts.reverse()  # [root, ..., attr]
            root, rest = parts[0], parts[1:]
            # self/cls: method binding, incl. one attribute-type hop
            if root in ("self", "cls") and fn is not None \
                    and fn.cls is not None:
                if len(rest) == 1:
                    return self._lookup_method(fn.cls, rest[0])
                if len(rest) == 2:
                    sub = fn.cls.attr_types.get(rest[0])
                    if sub is not None:
                        return self._lookup_method(sub, rest[1])
                return None
            # local ``x = Ctor()`` then ``x.m()``
            if local_types and root in local_types and len(rest) == 1:
                return self._lookup_method(local_types[root], rest[0])
            # module/class chains: mod.f, mod.Class, mod.sub.f, Class.m
            base = self._resolve_global(mi.modkey, root)
            base = _plain(base, keep_module=True)
            for i, part in enumerate(rest):
                if isinstance(base, tuple) and base[0] == "module":
                    base = _plain(
                        self._resolve_global(base[1], part),
                        keep_module=True)
                elif isinstance(base, ClassInfo):
                    return self._lookup_method(base, part) \
                        if i == len(rest) - 1 else None
                else:
                    return None
            return base if isinstance(base, (FunctionInfo, ClassInfo)) \
                else None
        return None

    # -- spawn sites / roots -------------------------------------------------
    def _spawn_site(self, call: ast.Call, fn: FunctionInfo, mi: ModuleInfo,
                    local_types: dict[str, ClassInfo],
                    ) -> tuple[str, ast.expr, str | None] | None:
        """(kind, target-expr, label-hint) when this call hands a
        callable to another execution context, else None."""
        dotted = canonical_dotted(call, mi)
        leaf = (dotted or "").split(".")[-1]
        # threading.Thread(target=...) — label from a literal name=
        if leaf == "Thread" and self._is_threading(dotted, mi):
            target = kwarg(call, "target")
            if target is None and call.args:
                return None  # positional group arg — not the idiom here
            if target is not None:
                name = kwarg(call, "name")
                hint = name.value if isinstance(name, ast.Constant) \
                    and isinstance(name.value, str) else None
                return ("thread", target, hint)
            return None
        if dotted in ("_thread.start_new_thread",
                      "thread.start_new_thread") and call.args:
            return ("thread", call.args[0], None)
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in ("submit", "map"):
                # only an executor handoff when the receiver is NOT a
                # resolvable project method of that name (sync/lanes.py
                # LanePool.submit) and the first arg IS a callable
                if self._resolve_callable_expr(call.func, mi, fn,
                                               local_types) is not None:
                    return None
                if call.args and self._looks_callable(
                        call.args[0], mi, fn, local_types):
                    return ("executor", call.args[0], None)
                return None
            if attr == "run_in_executor" and len(call.args) >= 2:
                return ("executor", call.args[1], None)
            if attr in ("create_task", "ensure_future"):
                if call.args:
                    return ("event-loop", call.args[0], None)
                return None
            if attr in ("call_soon", "call_soon_threadsafe",
                        "add_done_callback") and call.args:
                return ("event-loop", call.args[0], None)
            if attr in ("call_later", "call_at") and len(call.args) >= 2:
                return ("event-loop", call.args[1], None)
        if dotted in ("asyncio.create_task", "asyncio.ensure_future",
                      "asyncio.run") and call.args:
            return ("event-loop", call.args[0], None)
        return None

    def _is_threading(self, dotted: str | None, mi: ModuleInfo) -> bool:
        if dotted == "Thread":
            b = mi.bindings.get("Thread")
            return b is not None and b[0] == "ext-name" \
                and b[1] == "threading.Thread"
        return dotted == "threading.Thread"

    def _looks_callable(self, expr: ast.expr, mi: ModuleInfo,
                        fn: FunctionInfo,
                        local_types: dict[str, ClassInfo]) -> bool:
        if isinstance(expr, ast.Lambda):
            return True
        if isinstance(expr, ast.Call):  # partial(f, ...)
            d = canonical_dotted(expr, mi)
            return d in ("functools.partial", "partial")
        return self._resolve_callable_expr(expr, mi, fn,
                                           local_types) is not None

    def _register_root(self, kind: str, target_expr: ast.expr,
                       label_hint: str | None, call: ast.Call,
                       fn: FunctionInfo, mi: ModuleInfo,
                       local_types: dict[str, ClassInfo],
                       lambda_seq: int) -> int:
        # unwrap functools.partial(f, ...) — and for event-loop spawns a
        # coroutine-CALL target (``create_task(self._serve())``: the call
        # only builds the coroutine object; the body runs on the loop)
        if isinstance(target_expr, ast.Call):
            d = canonical_dotted(target_expr, mi)
            if d in ("functools.partial", "partial") and target_expr.args:
                target_expr = target_expr.args[0]
            elif kind == "event-loop":
                target_expr = target_expr.func
        if isinstance(target_expr, ast.Lambda):
            lambda_seq += 1
            qual = (f"{fn.qname.split('::', 1)[1]}"
                    f".<lambda#{lambda_seq}>")
            tgt = self._new_function(qual, fn.relpath, fn.modkey, fn.cls,
                                     target_expr)
            # the lambda body's calls resolve in the parent's scope
            self._resolve_lambda_body(tgt, fn, mi, local_types)
        else:
            resolved = self._resolve_callable_expr(target_expr, mi, fn,
                                                   local_types)
            tgt = resolved if isinstance(resolved, FunctionInfo) else None
            if tgt is None:
                return lambda_seq  # external/dynamic target: no root
        label = (EVENT_LOOP if kind == "event-loop"
                 else f"{kind}:{label_hint or tgt.short}")
        self.graph.roots.append(
            Root(label, kind, tgt, call.lineno, fn.relpath))
        return lambda_seq

    def _resolve_lambda_body(self, fn: FunctionInfo, parent: FunctionInfo,
                             mi: ModuleInfo,
                             local_types: dict[str, ClassInfo]) -> None:
        fn.local_names = parent.local_names
        consumed: set[int] = set()
        for node in walk_own_body(fn.node):
            if not isinstance(node, ast.Call) or id(node) in consumed:
                continue
            spawn = self._spawn_site(node, parent, mi, local_types)
            if spawn is not None and isinstance(spawn[1], ast.Call):
                consumed.add(id(spawn[1]))
            if spawn is not None:
                continue  # a lambda that spawns: root registration is
                # not modeled one level deep; just avoid a false edge
            for target in self._call_targets(node, parent, mi,
                                             local_types):
                fn.calls.append((target, node,
                                 dotted_name(node.func) or target.name))

    def _seed_convention_roots(self) -> None:
        for fn in list(self.graph.functions.values()):
            stage = STAGE_ROOTS.get(fn.name)
            if stage is not None and fn.cls is not None:
                self.graph.roots.append(
                    Root(stage, "stage", fn, fn.lineno, fn.relpath))
            if fn.is_async and top_dir(fn.relpath) in EVENT_LOOP_DIRS:
                self.graph.roots.append(
                    Root(EVENT_LOOP, "event-loop", fn, fn.lineno,
                         fn.relpath))


def _plain(obj: object, keep_module: bool = False) -> object | None:
    if isinstance(obj, tuple) and obj and obj[0] == "module":
        return obj if keep_module else None
    return obj


def kwarg(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def top_dir(relpath: str) -> str:
    return relpath.split("/")[0] if "/" in relpath else ""


def build_graph(files: dict[str, "FileContext"],
                root_name: str = "") -> CallGraph:
    """Build the project graph over already-parsed files (relpath ->
    FileContext). ``root_name`` is the scan root's directory name, so
    ``from <root_name>.sync import X`` resolves in fixture trees the
    way ``from spacedrive_tpu.sync import X`` does in the real one."""
    names = tuple(n for n in {root_name, "spacedrive_tpu"} if n)
    return _Builder(files, names).build()
