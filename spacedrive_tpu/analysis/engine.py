"""sdlint pass-manager: one parse per file, pluggable visitor passes,
per-line waivers, and a checked-in baseline ratchet.

Grown from ``utils/lint.py`` (the 135-line stdlib AST gate) into the
rigor layer the wedge postmortems of rounds 4-5 demanded: the single
most damaging production failure mode here is *conventional* — an
unguarded jax touchpoint that parks the lone job worker forever — and
conventions only hold when a test enforces them. The image ships no
external linters, so the framework is pure stdlib ``ast``.

Architecture
------------
- :class:`FileContext` parses each source file ONCE and hands every pass
  the same tree plus helpers (lines, scope, lazy parent map, waivers).
- :class:`AnalysisPass` is the plugin protocol: ``id`` + ``run(ctx)``
  yielding :class:`Finding` rows. Passes live in ``analysis/passes/``.
- :class:`PassManager` walks a tree, runs the registered passes, and
  drops findings waived on their own line:
  ``# lint: ok`` waives every pass; ``# lint: ok(pass-id, ...)`` waives
  only the named ones.
- The baseline ratchet (``analysis/baseline.txt``): pre-existing
  findings are keyed by ``relpath::pass-id::message`` (no line numbers,
  so unrelated edits don't churn the file) and allowed as a multiset;
  anything beyond the baseline is NEW and fails the run. Fixing an old
  finding leaves a stale entry — shrink the file with
  ``--update-baseline`` — so the debt only ratchets down.

Run: ``python -m spacedrive_tpu.analysis`` (exit 0 = no new findings).
``--json`` renders the same verdict machine-readably (editor/CI
tooling); ``--changed`` scopes the scan to files the working tree
touches vs HEAD (plus untracked) — the fast pre-commit form.
See docs/static-analysis.md for the pass list and workflow.
"""

from __future__ import annotations

import ast
import json
import re
import subprocess
import sys
import time
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator

#: matches both the blanket waiver ``# lint: ok`` and the scoped form
#: ``# lint: ok(pass-id, other-pass)``
WAIVER_RE = re.compile(r"#\s*lint:\s*ok(?:\s*\(([^)]*)\))?")

#: directory parts never scanned (build output, bench fixture cache)
SKIP_PARTS = ("_build", ".bench_cache", "__pycache__")


@dataclass(frozen=True)
class Finding:
    """One defect reported by one pass, pinned to a source line."""

    path: str       #: path as scanned (printable, clickable)
    relpath: str    #: posix path relative to the scan root (baseline key)
    lineno: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.lineno}: [{self.pass_id}] {self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "relpath": self.relpath,
                "line": self.lineno, "pass": self.pass_id,
                "message": self.message}

    @property
    def baseline_key(self) -> str:
        # no lineno: baselined findings must survive unrelated edits above
        # them, or the ratchet would churn on every refactor
        return f"{self.relpath}::{self.pass_id}::{self.message}"


class FileContext:
    """Everything a pass needs about one file, parsed exactly once."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.Module) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: dict[ast.AST, ast.AST] | None = None

    @classmethod
    def parse(cls, path: Path, root: Path | None = None) -> "FileContext":
        """Parse ``path``; raises SyntaxError (the manager converts it to a
        ``syntax`` finding so one broken file can't mask the rest)."""
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        try:
            relpath = path.resolve().relative_to(
                (root or path.parent).resolve()).as_posix()
        except ValueError:
            relpath = path.name
        return cls(path, relpath, source, tree)

    # -- scoping -------------------------------------------------------------
    @property
    def top_dir(self) -> str:
        """First directory component under the scan root ('' for files at
        the root itself) — how passes scope to production subsystems."""
        return self.relpath.split("/")[0] if "/" in self.relpath else ""

    def in_dirs(self, *dirs: str) -> bool:
        return self.top_dir in dirs

    # -- structure helpers ---------------------------------------------------
    def parent(self, node: ast.AST) -> ast.AST | None:
        """Lazy parent map over the shared tree (built once, all passes)."""
        if self._parents is None:
            self._parents = {}
            for outer in ast.walk(self.tree):
                for child in ast.iter_child_nodes(outer):
                    self._parents[child] = outer
        return self._parents.get(node)

    def finding(self, lineno: int, pass_id: str, message: str) -> Finding:
        return Finding(str(self.path), self.relpath, lineno, pass_id, message)

    # -- waivers -------------------------------------------------------------
    def waived(self, lineno: int, pass_id: str) -> bool:
        if not (0 < lineno <= len(self.lines)):
            return False
        m = WAIVER_RE.search(self.lines[lineno - 1])
        if m is None:
            return False
        scoped = m.group(1)
        if scoped is None:
            return True  # blanket ``# lint: ok``
        return pass_id in {p.strip() for p in scoped.split(",") if p.strip()}


class AnalysisPass:
    """Plugin protocol: subclass, set ``id``, yield findings from run()."""

    id: str = ""
    description: str = ""

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


class ProjectContext:
    """Every parsed file of one scan plus the lazily-built call graph —
    what a :class:`ProjectPass` analyzes. ``files`` maps relpath →
    :class:`FileContext` (parsed once by the manager, shared with the
    per-file passes)."""

    def __init__(self, files: dict[str, FileContext], root: Path) -> None:
        self.files = files
        self.root = root
        self._graph = None

    @property
    def graph(self):
        """The project call graph (analysis/callgraph.py), built on
        first use and shared by every project pass of the run."""
        if self._graph is None:
            from .callgraph import build_graph

            self._graph = build_graph(self.files, self.root.name)
        return self._graph


class ProjectPass(AnalysisPass):
    """A whole-program pass: sees every file of the scan at once (plus
    the call graph), so it can report the cross-module shapes —
    blocking I/O two calls below a lock, an event-loop stall through a
    helper in another module — that no per-file pass can."""

    def run_project(self, project: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        # a project pass has no per-file form; the manager routes it
        # through run_project() over however many files the scan holds
        return iter(())


def dotted_name(node: ast.AST) -> str | None:
    """'jax.numpy.zeros' for a Name/Attribute chain, else None. The shared
    call-classification helper every pass uses."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class PassManager:
    """Run registered passes over a file or tree; apply waivers.

    Per-file passes see one :class:`FileContext` at a time; project
    passes (:class:`ProjectPass`) see the whole parsed scan at once.
    ``check_file`` builds a single-file project (the fixture form:
    intra-file interprocedural analysis still works), ``check_tree``
    the full one, and ``check_changed`` parses the WHOLE tree to keep
    the call graph sound but prunes the project passes' reporting to
    the impacted component (changed functions plus every transitive
    caller — a callee edit can create or kill a finding anchored
    upstream)."""

    def __init__(self, passes: Iterable[AnalysisPass], root: Path) -> None:
        self.passes = list(passes)
        self.root = root
        self.file_passes = [p for p in self.passes
                            if not isinstance(p, ProjectPass)]
        self.project_passes = [p for p in self.passes
                               if isinstance(p, ProjectPass)]

    def _parse(self, path: Path) -> "tuple[FileContext | None, " \
                                    "Finding | None]":
        try:
            return FileContext.parse(path, self.root), None
        except SyntaxError as e:
            relpath = path.name
            try:
                relpath = path.resolve().relative_to(
                    self.root.resolve()).as_posix()
            except ValueError:
                pass
            return None, Finding(str(path), relpath, e.lineno or 0,
                                 "syntax", f"syntax error: {e.msg}")

    def _run_project(self, project: ProjectContext) -> list[Finding]:
        findings: list[Finding] = []
        for pp in self.project_passes:
            findings.extend(pp.run_project(project))
        return findings

    def _apply_waivers(self, findings: list[Finding],
                       files: dict[str, FileContext]) -> list[Finding]:
        out = []
        for f in findings:
            ctx = files.get(f.relpath)
            if ctx is not None and ctx.waived(f.lineno, f.pass_id):
                continue
            out.append(f)
        out.sort(key=lambda f: (f.relpath, f.lineno, f.pass_id, f.message))
        return out

    def check_file(self, path: Path) -> list[Finding]:
        ctx, syntax = self._parse(path)
        if ctx is None:
            return [syntax]
        findings: list[Finding] = []
        for ap in self.file_passes:
            findings.extend(ap.run(ctx))
        files = {ctx.relpath: ctx}
        findings.extend(self._run_project(ProjectContext(files, self.root)))
        return self._apply_waivers(findings, files)

    def _tree_paths(self) -> list[Path]:
        return [p for p in sorted(self.root.rglob("*.py"))
                if not any(part in SKIP_PARTS for part in p.parts)]

    def check_tree(self) -> list[Finding]:
        files: dict[str, FileContext] = {}
        findings: list[Finding] = []
        for path in self._tree_paths():
            ctx, syntax = self._parse(path)
            if ctx is None:
                findings.append(syntax)
                continue
            files[ctx.relpath] = ctx
            for ap in self.file_passes:
                findings.extend(ap.run(ctx))
        findings.extend(self._run_project(ProjectContext(files, self.root)))
        return self._apply_waivers(findings, files)

    def check_changed(self) -> tuple[list[Finding], list[str]]:
        """Scan only the ``*.py`` files under the root that git reports
        as modified vs HEAD or untracked — the fast pre-commit scope.
        The per-file passes run on exactly those files; the project
        passes run over the whole parsed tree (anything less would
        blind the call graph) with their findings pruned to the
        impacted component. Returns (findings, scanned-relpaths)."""
        changed = sorted(set(changed_files(self.root)))
        changed_rel: list[str] = []
        for path in changed:
            if any(part in SKIP_PARTS for part in path.parts):
                continue
            try:
                changed_rel.append(path.resolve().relative_to(
                    self.root.resolve()).as_posix())
            except ValueError:
                changed_rel.append(path.name)
        files: dict[str, FileContext] = {}
        findings: list[Finding] = []
        for path in self._tree_paths():
            ctx, syntax = self._parse(path)
            in_scope = syntax.relpath in changed_rel if ctx is None \
                else ctx.relpath in changed_rel
            if ctx is None:
                if in_scope:
                    findings.append(syntax)
                continue
            files[ctx.relpath] = ctx
            if in_scope:
                for ap in self.file_passes:
                    findings.extend(ap.run(ctx))
        if self.project_passes and changed_rel:
            project = ProjectContext(files, self.root)
            impacted = project.graph.impacted_files(changed_rel)
            findings.extend(f for f in self._run_project(project)
                            if f.relpath in impacted)
        return self._apply_waivers(findings, files), changed_rel


def changed_files(root: Path) -> list[Path]:
    """``*.py`` files under ``root`` the working tree touches: modified
    or added vs HEAD plus untracked (a brand-new module must not escape
    its own pre-commit run). Raises SystemExit outside a git checkout —
    --changed has no meaning there."""
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         cwd=str(root), capture_output=True, text=True)
    repo = Path(top.stdout.strip()) if top.returncode == 0 else root
    # each command's output is anchored by ITS convention — `diff` prints
    # repo-toplevel-relative paths regardless of cwd, `ls-files --others`
    # prints cwd-relative ones. Resolving each against its own anchor
    # (instead of probing both) keeps a root-relative untracked name from
    # aliasing a same-named file at the repo toplevel (which would make
    # a brand-new module silently escape its own pre-commit run).
    cmds = (
        (repo, ["git", "diff", "--name-only", "-z", "HEAD", "--", "*.py"]),
        (root, ["git", "ls-files", "--others", "--exclude-standard", "-z",
                "--", "*.py"]),
    )
    paths: set[Path] = set()
    for anchor, cmd in cmds:
        try:
            # -z: NUL-separated, UNQUOTED names — without it git's
            # core.quotepath octal-escapes any non-ASCII filename and the
            # mangled path would silently fail the exists() check below
            proc = subprocess.run(cmd, cwd=str(root), capture_output=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise SystemExit(f"--changed: cannot run git: {e}")
        if proc.returncode != 0:
            raise SystemExit("--changed needs a git checkout: "
                             + proc.stderr.decode(errors="replace").strip())
        for raw in proc.stdout.split(b"\0"):
            name = raw.decode("utf-8", errors="surrogateescape").strip()
            if name:
                paths.add((anchor / name).resolve())
    out: list[Path] = []
    for path in sorted(paths):
        if not path.exists():
            continue  # deleted files have no tree to scan
        try:
            path.relative_to(root.resolve())
        except ValueError:
            continue  # outside the scan root (tests/, bench.py, docs)
        out.append(path)
    return out


# -- baseline ratchet ---------------------------------------------------------

def load_baseline(path: Path) -> Counter:
    """Baseline file → multiset of finding keys. Missing file = empty."""
    counts: Counter = Counter()
    try:
        text = path.read_text()
    except OSError:
        return counts
    for line in text.splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            counts[line] += 1
    return counts


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    keys = sorted(f.baseline_key for f in findings)
    header = ("# sdlint baseline — pre-existing findings the ratchet "
              "tolerates.\n"
              "# One `relpath::pass-id::message` per line; new findings "
              "beyond this\n"
              "# multiset fail the run. Regenerate (only to SHRINK it) "
              "with:\n"
              "#   python -m spacedrive_tpu.analysis --update-baseline\n")
    path.write_text(header + "".join(k + "\n" for k in keys))


def ratchet(findings: list[Finding],
            baseline: Counter) -> tuple[list[Finding], Counter]:
    """Split findings into (new, stale-baseline-entries). A finding is NEW
    when its key occurs more times than the baseline allows."""
    budget = Counter(baseline)
    new: list[Finding] = []
    for f in findings:
        if budget[f.baseline_key] > 0:
            budget[f.baseline_key] -= 1
        else:
            new.append(f)
    stale = +budget  # entries the tree no longer produces: shrinkable debt
    return new, stale


# -- CLI ----------------------------------------------------------------------

def default_root() -> Path:
    """The spacedrive_tpu package directory (what the suite gates)."""
    return Path(__file__).resolve().parents[1]


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.txt"


def build_manager(root: Path,
                  pass_ids: list[str] | None = None) -> PassManager:
    from .passes import all_passes

    passes = all_passes()
    if pass_ids:
        known = {p.id for p in passes}
        unknown = [pid for pid in pass_ids if pid not in known]
        if unknown:
            raise SystemExit(f"unknown pass id(s): {', '.join(unknown)} "
                             f"(known: {', '.join(sorted(known))})")
        passes = [p for p in passes if p.id in pass_ids]
    return PassManager(passes, root)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m spacedrive_tpu.analysis",
        description="sdlint: multi-pass static analysis with a baseline "
                    "ratchet (exit 0 = no findings beyond the baseline)")
    parser.add_argument("root", nargs="?", default=None,
                        help="tree to scan (default: the spacedrive_tpu "
                             "package)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: analysis/baseline.txt)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding; exit 1 if any")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                             "(use only to shrink debt or adopt a new pass)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass ids to run (default: all)")
    parser.add_argument("--list-passes", action="store_true")
    fmt = parser.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable verdict on stdout (findings, "
                          "new, stale keys); exit code unchanged")
    fmt.add_argument("--sarif", action="store_true", dest="as_sarif",
                     help="SARIF 2.1.0 log on stdout (baselined findings "
                          "carry a suppression); exit code unchanged")
    parser.add_argument("--max-wall-s", type=float, default=None,
                        metavar="S",
                        help="fail (exit 1) if the scan itself takes longer "
                             "than S seconds — the pre-commit wall budget")
    parser.add_argument("--changed", action="store_true",
                        help="scan only *.py files modified vs HEAD or "
                             "untracked (git-scoped pre-commit run); the "
                             "ratchet still applies, stale entries for "
                             "unscanned files are not reported")
    args = parser.parse_args(argv)

    from .passes import all_passes

    if args.list_passes:
        for ap in all_passes():
            print(f"{ap.id:22s} {ap.description}")
        return 0

    root = Path(args.root) if args.root else default_root()
    baseline_path = (Path(args.baseline) if args.baseline
                     else default_baseline_path())
    pass_ids = ([p.strip() for p in args.passes.split(",") if p.strip()]
                if args.passes else None)
    manager = build_manager(root, pass_ids)
    scanned: list[str] | None = None
    t0 = time.monotonic()
    if args.changed:
        if args.update_baseline:
            raise SystemExit("--update-baseline needs the full tree "
                             "(a --changed subset would DROP every "
                             "baselined finding outside it)")
        findings, scanned = manager.check_changed()
    else:
        findings = manager.check_tree()
    wall_s = round(time.monotonic() - t0, 3)
    over_budget = (args.max_wall_s is not None and wall_s > args.max_wall_s)

    if args.update_baseline:
        save_baseline(baseline_path, findings)
        if args.as_json:
            print(json.dumps({"baseline": str(baseline_path),
                              "rewritten": len(findings)}, indent=2))
        else:
            print(f"baseline rewritten: {len(findings)} finding(s) -> "
                  f"{baseline_path}")
        return 0

    if args.no_baseline:
        if args.as_sarif:
            print(json.dumps(_sarif_doc(findings, findings, manager, root),
                             indent=2))
        elif args.as_json:
            print(json.dumps({
                "root": str(root), "baseline": None,
                "scanned": scanned, "wall_s": wall_s,
                "findings": [f.as_dict() for f in findings],
                "new": [f.as_dict() for f in findings], "stale": [],
            }, indent=2))
        else:
            for f in findings:
                print(f.render())
            print(f"{len(findings)} finding(s) in {wall_s}s")
        if over_budget:
            print(f"WALL BUDGET EXCEEDED: {wall_s}s > "
                  f"{args.max_wall_s}s", file=sys.stderr)
            return 1
        return 1 if findings else 0

    new, stale = ratchet(findings, load_baseline(baseline_path))
    if scanned is not None:
        # a changed-scope run never visits most files, so their baseline
        # entries look "stale" — only report staleness the scan can see
        scanned_set = set(scanned)
        stale = Counter({k: v for k, v in stale.items()
                         if k.split("::", 1)[0] in scanned_set})
    if args.as_sarif:
        print(json.dumps(_sarif_doc(findings, new, manager, root), indent=2))
        if over_budget:
            print(f"WALL BUDGET EXCEEDED: {wall_s}s > "
                  f"{args.max_wall_s}s", file=sys.stderr)
            return 1
        return 1 if new else 0
    if args.as_json:
        print(json.dumps({
            "root": str(root), "baseline": str(baseline_path),
            "scanned": scanned, "wall_s": wall_s,
            "findings": [f.as_dict() for f in findings],
            "new": [f.as_dict() for f in new],
            "stale": sorted(stale.elements()),
        }, indent=2))
        if over_budget:
            print(f"WALL BUDGET EXCEEDED: {wall_s}s > "
                  f"{args.max_wall_s}s", file=sys.stderr)
            return 1
        return 1 if new else 0
    for f in new:
        print(f.render())
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{len(findings) - len(new)} baselined, "
          f"{sum(stale.values())} stale baseline entr"
          f"{'y' if sum(stale.values()) == 1 else 'ies'} ({wall_s}s)")
    if stale:
        print("stale baseline entries (fixed findings — shrink with "
              "--update-baseline):")
        for key in sorted(stale):
            print(f"  {key}")
    if over_budget:
        print(f"WALL BUDGET EXCEEDED: {wall_s}s > "
              f"{args.max_wall_s}s", file=sys.stderr)
        return 1
    return 1 if new else 0


def _sarif_doc(findings: list[Finding], new: list[Finding],
               manager: PassManager, root: Path) -> dict:
    from .sarif import to_sarif

    return to_sarif(findings, new, manager.passes, root)
