"""``python -m spacedrive_tpu.analysis`` — run the ratcheted analysis."""

from .engine import main

if __name__ == "__main__":
    raise SystemExit(main())
