"""Typed-client codegen: router schema → client/core.ts + procedures.js.

The reference generates ``packages/client/src/core.ts`` by running an
rspc/specta export test (core/src/api/mod.rs:205-212) and consumes it with
a node/library scope split (packages/client/src/rspc.tsx:13-43). Here the
same contract is rendered from the live router schema plus the reviewed
type map (api/types.py):

- ``client/core.ts`` — the TypeScript contract: shared row interfaces, a
  ``Procedures`` union per kind carrying each procedure's key/input/result,
  the node/library key-union split, and a ``procedures`` const map.
- ``client/procedures.js`` — the runtime mirror the vanilla-JS web explorer
  loads (<script src="/client/procedures.js">): ``window.SD_PROCEDURES``
  with kind+scope per key. The explorer's rspc() helper refuses keys that
  aren't in it, so the generated artifact is load-bearing, not decorative.

Regenerate with ``python -m spacedrive_tpu.api.codegen`` after any router
change; tests/test_ts_client.py fails on a stale file (golden gate).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from .types import TS_PRELUDE, TYPES

HEADER = ("// GENERATED FILE — do not edit.\n"
          "// Regenerate: python -m spacedrive_tpu.api.codegen\n"
          "// Contract source: spacedrive_tpu/api/types.py + the mounted "
          "router schema.\n")


def _entry(proc: dict[str, Any]) -> str:
    arg, result = TYPES.get(proc["key"], ("unknown", "unknown"))
    return (f'\t{{ key: "{proc["key"]}", input: {arg}, result: {result} }}')


def generate_core_ts(schema: dict[str, Any]) -> str:
    procs = schema["procedures"]
    by_kind: dict[str, list[dict]] = {"query": [], "mutation": [],
                                      "subscription": []}
    for p in procs:
        by_kind[p["kind"]].append(p)

    parts = [HEADER, "", TS_PRELUDE]
    parts.append("export type Procedures = {")
    for kind, plural in (("query", "queries"), ("mutation", "mutations"),
                         ("subscription", "subscriptions")):
        entries = " |\n".join(_entry(p) for p in by_kind[kind]) or "never"
        parts.append(f"  {plural}:\n{entries},")
    parts.append("};")
    parts.append("")

    lib = [p["key"] for p in procs if p["scope"] == "library"]
    node = [p["key"] for p in procs if p["scope"] != "library"]
    parts.append("/** Library-scoped procedures take a library_id — the "
                 "client-side split of rspc.tsx:13-43. */")
    parts.append("export type LibraryProcedureKey =")
    parts.append(" |\n".join(f'\t"{k}"' for k in lib) + ";")
    parts.append("export type NodeProcedureKey =")
    parts.append(" |\n".join(f'\t"{k}"' for k in node) + ";")
    parts.append("export type ProcedureKey = LibraryProcedureKey | "
                 "NodeProcedureKey;")
    parts.append("")
    parts.append("export const procedures = {")
    for p in procs:
        parts.append(f'\t"{p["key"]}": {{ kind: "{p["kind"]}", '
                     f'scope: "{p["scope"]}" }},')
    parts.append("} as const;")
    return "\n".join(parts) + "\n"


def generate_procedures_js(schema: dict[str, Any]) -> str:
    table = {p["key"]: {"kind": p["kind"], "scope": p["scope"]}
             for p in schema["procedures"]}
    return (HEADER +
            "window.SD_PROCEDURES = " +
            json.dumps(table, indent=1, sort_keys=True) + ";\n")


def client_dir() -> Path:
    return Path(__file__).resolve().parents[2] / "client"


def write_client(schema: dict[str, Any], out_dir: Path | None = None) -> list[Path]:
    out = out_dir or client_dir()
    out.mkdir(parents=True, exist_ok=True)
    core = out / "core.ts"
    procs = out / "procedures.js"
    core.write_text(generate_core_ts(schema))
    procs.write_text(generate_procedures_js(schema))
    return [core, procs]


def main() -> int:
    import sys

    schema_path = Path(__file__).resolve().parents[2] / "schema" / "api.json"
    if "--from-snapshot" in sys.argv and schema_path.exists():
        # opt-in fast path: the schema/api.json snapshot (refreshed by the
        # test suite) — can lag the routers, so it is NOT the default
        schema = json.loads(schema_path.read_text())
    else:
        # authoritative: mount a throwaway node and export the live schema
        import tempfile

        from ..node import Node

        with tempfile.TemporaryDirectory(prefix="sd_codegen_") as tmp:
            node = Node(tmp, probe_accelerator=False, watch_locations=False)
            try:
                schema = node.router.schema()
            finally:
                node.shutdown()
    for path in write_client(schema):
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
