"""library.* procedures (api/libraries.rs): list, statistics, create, edit,
delete."""

from __future__ import annotations

from ...statistics import compute_statistics
from ..invalidate import invalidate_query


def mount(router) -> None:
    @router.query("libraries.list")
    def list_libraries(node, _arg):
        return [{"id": lib.id, "name": lib.name,
                 "description": lib.config.get("description", ""),
                 "instance_id": lib.instance_id,
                 "instance_pub_id": (lib.instance() or {}).get("pub_id")}
                for lib in node.libraries.list()]

    @router.library_query("libraries.statistics", pool=True, replica=False)
    def statistics(node, library, _arg):
        """Recomputed on query (api/libraries.rs:47). Pool-pure (ISSUE 15
        satellite): a pure read over (library.db, node.data_dir) — the
        snapshot-row persistence the reference does on query moved to
        statistics.update_statistics for write-capable callers, so this
        handler runs in serve-pool workers under the worker-purity lint.
        ``replica=False`` (ISSUE 19): the node.data_dir disk stats are
        node-specific — a converged peer would still answer with ITS OWN
        free space, so this stays off the replica tier (and out of the
        replica-purity lint's scope)."""
        row = dict(compute_statistics(library.db, node.data_dir))
        row.pop("date_captured", None)
        return row

    @router.mutation("libraries.create")
    def create(node, arg):
        lib = node.libraries.create(arg["name"], arg.get("description", ""))
        invalidate_query(lib, "libraries.list")
        return {"id": lib.id, "name": lib.name}

    @router.mutation("libraries.edit")
    def edit(node, arg):
        node.libraries.edit(arg["id"], name=arg.get("name"),
                            description=arg.get("description"))
        return None

    @router.mutation("libraries.delete")
    def delete(node, library_id: str):
        node.libraries.delete(library_id)
        return None
