"""notifications.* (api/notifications.rs:41-167): get, dismiss, dismissAll,
listen subscription, test helpers."""

from __future__ import annotations

from ...notifications import (dismiss_all, dismiss_notification,
                              emit_library_notification,
                              emit_node_notification, get_notifications)
from ._util import filtered_subscription


def mount(router) -> None:
    @router.query("notifications.get")
    def get(node, _arg):
        return get_notifications(node)

    @router.mutation("notifications.dismiss")
    def dismiss(node, arg):
        dismiss_notification(node, arg["source"], arg["id"],
                             library_id=arg.get("library_id"))
        return None

    @router.mutation("notifications.dismissAll")
    def dismiss_all_(node, _arg):
        dismiss_all(node)
        return None

    @router.subscription("notifications.listen")
    def listen(node, _arg):
        return filtered_subscription(node, {"notification"})

    @router.mutation("notifications.test")
    def test(node, _arg):
        return emit_node_notification(node, {"title": "Test",
                                             "content": "Test notification"})

    @router.library_mutation("notifications.testLibrary")
    def test_library(node, library, _arg):
        return emit_library_notification(library, {"title": "Test",
                                                   "content": "Library test"})
