"""telemetry.* procedures (ISSUE 5): the rspc view of the unified
registry and the per-job trace trees.

- ``telemetry.snapshot`` — metrics + recent events + recent trace
  summaries in one JSON document (what ``python -m
  spacedrive_tpu.telemetry`` pretty-prints).
- ``telemetry.jobTrace`` — the nested span tree of one job run (in-memory
  ring first, then the exported JSONL under ``<data_dir>/logs/traces/``),
  or null when nothing was recorded (``SD_TELEMETRY=off`` runs).
"""

from __future__ import annotations

from ... import telemetry
from ..router import ApiError


def mount(router) -> None:
    @router.query("telemetry.snapshot")
    def snapshot(node, _arg):
        """Full telemetry state of this node process."""
        return telemetry.snapshot()

    @router.query("telemetry.jobTrace")
    def job_trace(node, arg):
        """Span tree for a job id (arg: the id string, or
        {"job_id": ...}); null when no trace was recorded."""
        job_id = arg.get("job_id") if isinstance(arg, dict) else arg
        if not job_id or not isinstance(job_id, str):
            raise ApiError("telemetry.jobTrace needs a job id")
        return telemetry.job_trace(job_id, data_dir=node.data_dir)
