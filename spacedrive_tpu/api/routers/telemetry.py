"""telemetry.* procedures (ISSUE 5 + 7): the rspc view of the unified
registry, the per-job trace trees, the live flight recorder, and the
alert evaluator.

- ``telemetry.snapshot`` — metrics + recent events + recent trace
  summaries in one JSON document (what ``python -m
  spacedrive_tpu.telemetry`` pretty-prints).
- ``telemetry.jobTrace`` — the nested span tree of one job run (in-memory
  ring first, then the exported JSONL under ``<data_dir>/logs/traces/``),
  or null when nothing was recorded (``SD_TELEMETRY=off`` runs).
- ``telemetry.watch`` — SUBSCRIPTION: the flight-recorder event stream
  (job transitions, fault firings, router flips, sync sessions, alert
  edges) live over the websocket; the SSE twin is ``GET
  /telemetry/stream`` on the shell.
- ``telemetry.alerts`` — every alert rule with its live firing state.
- ``telemetry.sloStatus`` — the SLO engine's objectives with live SLI,
  error-budget remaining and multi-window burn rates (ISSUE 20), plus
  the rspc dispatch-admission budget state.
"""

from __future__ import annotations

from ... import telemetry
from ..router import ApiError
from ._util import filtered_subscription


def mount(router) -> None:
    @router.query("telemetry.snapshot")
    def snapshot(node, _arg):
        """Full telemetry state of this node process."""
        return telemetry.snapshot()

    @router.query("telemetry.jobTrace")
    def job_trace(node, arg):
        """Span tree for a job id (arg: the id string, or
        {"job_id": ...}); null when no trace was recorded."""
        job_id = arg.get("job_id") if isinstance(arg, dict) else arg
        if not job_id or not isinstance(job_id, str):
            raise ApiError("telemetry.jobTrace needs a job id")
        return telemetry.job_trace(job_id, data_dir=node.data_dir)

    @router.subscription("telemetry.watch")
    def watch(node, _arg):
        """Live flight-recorder tail: one event per telemetry.event()
        (the Node bridges the ring's hooks onto its event bus)."""
        return filtered_subscription(node, {"telemetry.event"})

    @router.query("telemetry.alerts")
    def alerts(node, _arg):
        """The SLO/alert rule set with live state (telemetry/alerts.py)."""
        evaluator = getattr(node, "alerts", None)
        return {"rules": evaluator.state() if evaluator is not None else []}

    @router.query("telemetry.sloStatus")
    def slo_status(node, _arg):
        """SLO objectives with live SLI / error budget / burn rates
        (telemetry/slo.py), plus dispatch-admission budget state — the
        serving tier's "are we inside our promises" page (ISSUE 20)."""
        engine = getattr(node, "slo", None)
        budget = getattr(node, "dispatch_budget", None)
        return {
            "objectives": engine.status() if engine is not None else [],
            "dispatch_admission":
                budget.status() if budget is not None else None,
        }

    @router.query("telemetry.requestStats")
    def request_stats(node, arg):
        """Serving-tier request telemetry (ISSUE 10): per-procedure
        p50/p95/p99 latency estimates, outcome/payload counts, in-flight,
        and the slow-request ring with full span trees (arg: optional
        {"slow_limit": n})."""
        from ...telemetry import requests as rq

        limit = 16
        if isinstance(arg, dict):
            try:
                limit = max(0, min(int(arg.get("slow_limit", 16)),
                                   rq.SLOW_RING))
            except (TypeError, ValueError):
                raise ApiError("slow_limit must be an integer")
        out = rq.stats(slow_limit=limit)
        # serve-pool fold-in (ISSUE 11): the multi-process reader pool's
        # worker/cache/restart state, when one is running (null in the
        # degraded in-process mode)
        pool = getattr(node, "reader_pool", None)
        out["serve_pool"] = pool.status() if pool is not None else None
        return out
