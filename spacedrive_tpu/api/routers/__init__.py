"""Per-domain sub-routers, merged by api.router.mount (the 17-router layout
of core/src/api/mod.rs:102-203)."""

# imported for its import-time sd_delta_* metric families: api.router.mount
# runs at Node construction, so the families render on /metrics (zero
# samples) even when SD_P2P_DISABLED keeps the p2p manager itself from
# starting — the observability.md drift gate holds in both directions
from ...p2p import delta as _delta  # noqa: F401
