"""Per-domain sub-routers, merged by api.router.mount (the 17-router layout
of core/src/api/mod.rs:102-203)."""
