"""p2p.* procedures (api/p2p.rs). The networking layer wires real handlers;
until a peer mesh is up these surface the node's own state and validate
the procedure contract."""

from __future__ import annotations

from ..router import ApiError
from ._util import filtered_subscription


def mount(router) -> None:
    @router.subscription("p2p.events")
    def events(node, _arg):
        return filtered_subscription(node, {"p2p"})

    @router.query("p2p.nlmState")
    def nlm_state(node, _arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            return {}
        return p2p.nlm_state()

    @router.mutation("p2p.spacedrop")
    def spacedrop(node, arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            raise ApiError("p2p is not running", code=503)
        return p2p.spacedrop(arg["peer_id"], arg["paths"])

    @router.mutation("p2p.acceptSpacedrop")
    def accept_spacedrop(node, arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            raise ApiError("p2p is not running", code=503)
        p2p.accept_spacedrop(arg["id"], arg.get("target_dir"))
        return None

    @router.mutation("p2p.cancelSpacedrop")
    def cancel_spacedrop(node, arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            raise ApiError("p2p is not running", code=503)
        p2p.cancel_spacedrop(arg["id"])
        return None

    @router.mutation("p2p.pair")
    def pair(node, arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            raise ApiError("p2p is not running", code=503)
        return p2p.pair(arg["peer_id"], arg["library_id"])

    @router.mutation("p2p.pairingResponse")
    def pairing_response(node, arg):
        p2p = getattr(node, "p2p", None)
        if p2p is None:
            raise ApiError("p2p is not running", code=503)
        p2p.pairing_response(arg["pairing_id"], arg["decision"])
        return None
