"""p2p.* procedures (api/p2p.rs): events subscription, NLM state, spacedrop
send/accept/cancel, pairing originate/response — backed by the live
P2PManager (spacedrive_tpu/p2p). A node booted with ``p2p_enabled: false``
returns 503 from the mutations, matching a reference build without the
p2p feature."""

from __future__ import annotations

from ..router import ApiError
from ._util import filtered_subscription


def _p2p(node):
    p2p = getattr(node, "p2p", None)
    if p2p is None:
        raise ApiError("p2p is not running", code=503)
    return p2p


def mount(router) -> None:
    @router.subscription("p2p.events")
    def events(node, _arg):
        return filtered_subscription(node, {"p2p"})

    @router.query("p2p.nlmState")
    def nlm_state(node, _arg):
        p2p = getattr(node, "p2p", None)
        return {} if p2p is None else p2p.nlm_state()

    @router.query("p2p.peers")
    def peers(node, _arg):
        """Discovered + connected peers with metadata (incl. accelerator
        inventory — the TPU-native remote-hasher routing input)."""
        p2p = getattr(node, "p2p", None)
        return [] if p2p is None else p2p.peer_list()

    @router.query("p2p.identity")
    def identity(node, _arg):
        """This node's RemoteIdentity + listen port (peer address card)."""
        p2p = _p2p(node)
        return {"identity": p2p.remote_identity.encode(), "port": p2p.port}

    @router.mutation("p2p.spacedrop")
    def spacedrop(node, arg):
        return _p2p(node).spacedrop(arg["peer_id"], arg["paths"])

    @router.mutation("p2p.spacedropDelta")
    def spacedrop_delta(node, arg):
        """Delta-aware drop: chunk-manifest negotiation ships only the
        chunks the receiver lacks (docs/architecture/chunking.md)."""
        return _p2p(node).spacedrop_delta(arg["peer_id"], arg["paths"])

    @router.mutation("p2p.acceptSpacedrop")
    def accept_spacedrop(node, arg):
        """target_dir omitted/None declines the drop (api/p2p.rs: accept
        with None file path is the decline signal)."""
        try:
            _p2p(node).accept_spacedrop(arg["id"], arg.get("target_dir"))
        except KeyError as e:
            raise ApiError(str(e), code=404) from e
        return None

    @router.mutation("p2p.cancelSpacedrop")
    def cancel_spacedrop(node, arg):
        _p2p(node).cancel_spacedrop(arg["id"])
        return None

    @router.mutation("p2p.pair")
    def pair(node, arg):
        return _p2p(node).pair(arg["peer_id"])

    @router.mutation("p2p.pairingResponse")
    def pairing_response(node, arg):
        try:
            _p2p(node).pairing_response(arg["pairing_id"], arg["decision"])
        except KeyError as e:
            raise ApiError(str(e), code=404) from e
        return None

    @router.mutation("p2p.debugConnect")
    def debug_connect(node, arg):
        """Handshake a host:port directly (static-peer path; returns the
        peer's identity). The test/ops analogue of mDNS discovery."""
        p2p = _p2p(node)

        async def _connect():
            reader, writer, meta = await p2p.open_stream(arg["addr"])
            writer.close()
            return meta["identity"]

        return p2p.run_coro(_connect(), timeout=30)
