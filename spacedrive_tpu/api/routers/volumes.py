"""volumes.list (api/volumes.rs), backed by /proc/mounts enumeration."""

from __future__ import annotations

from ...volumes import get_volumes


def mount(router) -> None:
    @router.query("volumes.list")
    def list_volumes(node, _arg):
        return get_volumes()
