"""preferences.{get,update} (api/preferences.rs)."""

from __future__ import annotations

from ...preferences import get_preferences, update_preferences


def mount(router) -> None:
    @router.library_query("preferences.get")
    def get(node, library, _arg):
        return get_preferences(library)

    @router.library_mutation("preferences.update")
    def update(node, library, tree):
        update_preferences(library, tree or {})
        return None
