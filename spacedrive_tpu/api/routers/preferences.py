"""preferences.{get,update} (api/preferences.rs)."""

from __future__ import annotations

from ...preferences import get_preferences, update_preferences


def mount(router) -> None:
    @router.library_query("preferences.get", pool=True)
    def get(node, library, _arg):
        # pure library.db read (preferences.py walks the preference table
        # only), so it serves byte-identically from the worker pool —
        # serving rung (c), proven by test_serving_pool.py
        return get_preferences(library)

    @router.library_mutation("preferences.update")
    def update(node, library, tree):
        update_preferences(library, tree or {})
        return None
