"""spaces.* / albums.* / labels.* procedures.

The reference defines these models in schema.prisma (:323-454) but ships
no procedures for them (the frontend's spaces UI is mock data); here the
schema gets a working surface: collection CRUD, membership, and member
listings shaped like search.paths rows so the explorer renders them with
the same grid.
"""

from __future__ import annotations

from ...models import Album, Space
from ...objects import collections as col
from ..router import ApiError


def _require(arg, *keys):
    """Missing required fields are a 400-class ApiError, not a bare
    KeyError surfacing as a 500 (matches the other routers' argument
    handling)."""
    if not isinstance(arg, dict):
        raise ApiError(f"expected an object argument with {list(keys)}")
    missing = [k for k in keys if k not in arg]
    if missing:
        raise ApiError(f"missing required argument field(s): {missing}")
    return arg


def _mount_collection(router, key: str, model) -> None:
    @router.library_query(f"{key}.list")
    def list_all(node, library, _arg):
        return col.list_collections(library, model)

    @router.library_mutation(f"{key}.create")
    def create(node, library, arg):
        extra = {}
        if model is Space and isinstance(arg, dict) and arg.get("description"):
            extra["description"] = arg["description"]
        if model is Album:
            extra["is_hidden"] = bool(
                isinstance(arg, dict) and arg.get("is_hidden"))
        if isinstance(arg, dict):
            name = _require(arg, "name")["name"]
        elif isinstance(arg, str):
            name = arg
        else:
            raise ApiError("expected a name string or {name: ...} object")
        return col.create_collection(library, model, name, **extra)

    @router.library_mutation(f"{key}.update")
    def update(node, library, arg):
        _require(arg, "id")
        values = {k: arg.get(k) for k in ("name", "description", "is_hidden")
                  if k in model.FIELDS}
        col.update_collection(library, model, arg["id"], **values)
        return None

    @router.library_mutation(f"{key}.delete")
    def delete(node, library, collection_id: int):
        col.delete_collection(library, model, collection_id)
        return None

    @router.library_mutation(f"{key}.addObjects")
    def add_objects(node, library, arg):
        _require(arg, "id", "object_ids")
        return col.set_membership(library, model, arg["id"],
                                  arg["object_ids"])

    @router.library_mutation(f"{key}.removeObjects")
    def remove_objects(node, library, arg):
        _require(arg, "id", "object_ids")
        return col.set_membership(library, model, arg["id"],
                                  arg["object_ids"], remove=True)

    @router.library_query(f"{key}.objects")
    def objects(node, library, collection_id: int):
        return col.collection_objects(library, model, collection_id)


def mount(router) -> None:
    _mount_collection(router, "spaces", Space)
    _mount_collection(router, "albums", Album)

    @router.library_query("labels.list")
    def labels_list(node, library, _arg):
        return col.list_labels(library)

    @router.library_query("labels.getForObject")
    def labels_for_object(node, library, object_id: int):
        return col.labels_for_object(library, object_id)

    @router.library_mutation("labels.assign")
    def labels_assign(node, library, arg):
        _require(arg, "name", "object_ids")
        label = col.ensure_label(library, arg["name"])
        return col.label_objects(library, label["id"], arg["object_ids"],
                                 remove=bool(arg.get("remove")))
