"""Shared helpers for router modules."""

from __future__ import annotations

from typing import Any, Callable


def filtered_subscription(node, kinds: set[str], library_id: str | None = None,
                          predicate: Callable[[Any], bool] | None = None):
    """Event-bus subscription annotated with a filter; transports apply
    ``sub.filter(event)`` before forwarding (reference subscriptions stream
    only their own CoreEvent variants)."""
    sub = node.events.subscribe()
    def _filter(ev) -> bool:
        if kinds and ev.kind not in kinds:
            return False
        if library_id is not None and getattr(ev, "library_id", None) not in (None, library_id):
            return False
        return predicate(ev) if predicate else True
    sub.filter = _filter
    return sub
