"""locations.* procedures (api/locations.rs): CRUD, rescans, online
subscription + the indexer_rules sub-router."""

from __future__ import annotations

from ...locations import (create_location, delete_location,
                          light_scan_location, scan_location)
from ...locations.rules import (IndexerRuleSpec, rules_for_location,
                                seed_rules)
from ...models import IndexerRule, IndexerRulesInLocation, Location
from ..invalidate import invalidate_query
from ..router import ApiError
from ._util import filtered_subscription


def mount(router) -> None:
    @router.library_query("locations.list")
    def list_locations(node, library, _arg):
        rows = library.db.find(Location, order_by="name")
        online = set(node.locations.online_ids(library.id)) if node.locations else set()
        for r in rows:
            r["online"] = r["id"] in online
        return rows

    @router.library_query("locations.get", pool=True)
    def get(node, library, location_id: int):
        row = library.db.find_one(Location, {"id": location_id})
        if row is None:
            raise ApiError("location not found", code=404)
        return row

    @router.library_query("locations.getWithRules", pool=True)
    def get_with_rules(node, library, location_id: int):
        row = library.db.find_one(Location, {"id": location_id})
        if row is None:
            raise ApiError("location not found", code=404)
        row["indexer_rules"] = [
            {"name": s.name, "rules": s.rules, "default": s.default}
            for s in rules_for_location(library.db, location_id)]
        return row

    @router.library_mutation("locations.create")
    def create(node, library, arg):
        row = create_location(library, arg["path"], name=arg.get("name"),
                              indexer_rule_names=arg.get("indexer_rules"),
                              hasher=arg.get("hasher", "hybrid"),
                              dry_run=arg.get("dry_run", False))
        if not arg.get("dry_run"):
            scan_location(library, row["id"])
        return row

    @router.library_mutation("locations.update")
    def update(node, library, arg):
        db = library.db
        location_id = arg["id"]
        if db.find_one(Location, {"id": location_id}) is None:
            raise ApiError("location not found", code=404)
        values = {k: arg[k] for k in
                  ("name", "hidden", "generate_preview_media", "hasher")
                  if k in arg}
        if values:
            db.update(Location, {"id": location_id}, values)
        if "indexer_rules" in arg:
            db.delete(IndexerRulesInLocation, {"location_id": location_id})
            for rule_name in arg["indexer_rules"]:
                rule = db.find_one(IndexerRule, {"name": rule_name})
                if rule:
                    db.insert(IndexerRulesInLocation,
                              {"location_id": location_id,
                               "indexer_rule_id": rule["id"]}, or_ignore=True)
        invalidate_query(library, "locations.list")
        return None

    @router.library_mutation("locations.delete")
    def delete(node, library, location_id: int):
        delete_location(library, location_id)
        return None

    @router.library_mutation("locations.relink")
    def relink(node, library, path: str):
        """Re-bind a moved location directory via its .spacedrive metadata
        (location/mod.rs relink)."""
        from ...locations import read_metadata

        meta = read_metadata(path)
        if meta is None or library.id not in meta.get("libraries", {}):
            raise ApiError("no spacedrive metadata for this library here")
        location_id = meta["libraries"][library.id]["location_id"]
        library.db.update(Location, {"id": location_id}, {"path": str(path)})
        invalidate_query(library, "locations.list")
        return location_id

    @router.library_mutation("locations.addLibrary")
    def add_library(node, library, arg):
        """Add an already-spacedrive'd directory to THIS library too
        (LocationCreateArgs::add_library — the dotfile keeps per-library
        entries so several libraries can track one directory)."""
        from ...locations import create_location

        row = create_location(library, arg["path"], name=arg.get("name"),
                              indexer_rule_names=arg.get("indexer_rules"),
                              hasher=arg.get("hasher", "hybrid"))
        scan_location(library, row["id"])  # same pipeline kick as create
        invalidate_query(library, "locations.list")
        return row

    @router.library_mutation("locations.fullRescan")
    def full_rescan(node, library, arg):
        return scan_location(library, arg["location_id"])

    @router.library_mutation("locations.subPathRescan")
    def sub_path_rescan(node, library, arg):
        return scan_location(library, arg["location_id"],
                             sub_path=arg.get("sub_path"))

    @router.library_mutation("locations.quickRescan")
    def quick_rescan(node, library, arg):
        light_scan_location(library, arg["location_id"],
                            arg.get("sub_path", ""))
        invalidate_query(library, "search.paths")
        return None

    @router.library_subscription("locations.online")
    def online(node, library, _arg):
        return filtered_subscription(node, {"locations_online"}, library.id)

    # -- indexer_rules sub-router ------------------------------------------
    @router.library_query("locations.indexer_rules.list")
    def rules_list(node, library, _arg):
        seed_rules(library.db)
        return library.db.find(IndexerRule, order_by="name")

    @router.library_query("locations.indexer_rules.get", pool=True)
    def rules_get(node, library, rule_id: int):
        row = library.db.find_one(IndexerRule, {"id": rule_id})
        if row is None:
            raise ApiError("rule not found", code=404)
        return row

    @router.library_query("locations.indexer_rules.listForLocation", pool=True)
    def rules_for_loc(node, library, location_id: int):
        return [{"name": s.name, "rules": s.rules, "default": s.default}
                for s in rules_for_location(library.db, location_id)]

    @router.library_mutation("locations.indexer_rules.create")
    def rules_create(node, library, arg):
        spec = IndexerRuleSpec(name=arg["name"], default=False,
                               rules={int(k): v for k, v in arg["rules"].items()})
        rule_id = library.db.insert(IndexerRule, spec.to_row())
        # rules reads are pool-cached (ISSUE 11): a write with no event
        # would serve stale rule rows until an unrelated bump
        invalidate_query(library, "locations.indexer_rules.list")
        return rule_id

    @router.library_mutation("locations.indexer_rules.delete")
    def rules_delete(node, library, rule_id: int):
        row = library.db.find_one(IndexerRule, {"id": rule_id})
        if row and row["default"]:
            raise ApiError("cannot delete a system rule")
        library.db.delete(IndexerRulesInLocation, {"indexer_rule_id": rule_id})
        library.db.delete(IndexerRule, {"id": rule_id})
        invalidate_query(library, "locations.indexer_rules.list")
        # the delete also removed per-location assignments — refresh the
        # key-routed frontend caches of both rule views
        invalidate_query(library, "locations.indexer_rules.listForLocation")
        invalidate_query(library, "locations.getWithRules")
        return None
