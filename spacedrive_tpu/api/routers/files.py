"""files.* procedures (api/files.rs): object/file getters + mutations +
fs-job launchers (copy/cut/delete/erase/duplicate/rename/createDirectory)."""

from __future__ import annotations

from pathlib import Path

from ...models import FilePath, MediaData, Object, utc_now
from ...objects.crypto_jobs import FileDecryptorJob, FileEncryptorJob
from ...objects.fs import (FileCopierJob, FileCutterJob, FileDeleterJob,
                           FileEraserJob, create_directory, create_file,
                           file_path_abs)
from ...objects.media.metadata import extract_media_data
from ..invalidate import invalidate_query
from ..router import ApiError


def _object_of(library, object_id: int) -> dict:
    row = library.db.find_one(Object, {"id": object_id})
    if row is None:
        raise ApiError(f"object {object_id} not found", code=404)
    return row


def mount(router) -> None:
    @router.library_query("files.get", pool=True)
    def get(node, library, arg):
        """Object + its file_paths by object id or file_path id."""
        db = library.db
        if arg.get("file_path_id") is not None:
            fp = db.find_one(FilePath, {"id": arg["file_path_id"]})
            if fp is None:
                raise ApiError("file_path not found", code=404)
            obj = db.find_one(Object, {"id": fp["object_id"]}) if fp["object_id"] else None
        else:
            obj = _object_of(library, arg["object_id"])
            fp = None
        paths = db.find(FilePath, {"object_id": obj["id"]}) if obj else ([fp] if fp else [])
        return {"object": obj, "file_paths": paths}

    @router.library_query("files.getPath", pool=True)
    def get_path(node, library, file_path_id: int):
        _row, path = file_path_abs(library.db, file_path_id)
        return str(path)

    @router.library_query("files.getMediaData", pool=True)
    def get_media_data(node, library, object_id: int):
        return library.db.find_one(MediaData, {"object_id": object_id})

    @router.query("files.getEphemeralMediaData")
    def get_ephemeral_media_data(node, path: str):
        ext = Path(path).suffix.lstrip(".").lower()
        return extract_media_data(path, ext)

    @router.library_mutation("files.setNote")
    def set_note(node, library, arg):
        obj = _object_of(library, arg["object_id"])
        library.db.update(Object, {"id": obj["id"]}, {"note": arg.get("note")})
        _sync_update(library, obj, "note", arg.get("note"))
        invalidate_query(library, "search.paths")
        return None

    @router.library_mutation("files.setFavorite")
    def set_favorite(node, library, arg):
        obj = _object_of(library, arg["object_id"])
        library.db.update(Object, {"id": obj["id"]},
                          {"favorite": bool(arg.get("favorite"))})
        _sync_update(library, obj, "favorite", bool(arg.get("favorite")))
        invalidate_query(library, "search.paths")
        return None

    @router.library_mutation("files.updateAccessTime")
    def update_access_time(node, library, object_id: int):
        library.db.update(Object, {"id": object_id},
                          {"date_accessed": utc_now()})
        # invalidate like every sibling write: files.get responses are
        # pool-cached (ISSUE 11) — a write with no event would be served
        # stale until an unrelated bump
        invalidate_query(library, "files.get")
        return None

    @router.library_mutation("files.removeAccessTime")
    def remove_access_time(node, library, object_id: int):
        library.db.update(Object, {"id": object_id}, {"date_accessed": None})
        invalidate_query(library, "files.get")
        return None

    @router.library_mutation("files.renameFile")
    def rename_file(node, library, arg):
        """Disk rename + row update (api/files.rs renameFile)."""
        db = library.db
        row, path = file_path_abs(db, arg["file_path_id"])
        new_name = arg["new_name"]
        if "/" in new_name or new_name in (".", ".."):
            raise ApiError(f"invalid name {new_name!r}")
        target = path.with_name(new_name)
        if target.exists():
            raise ApiError(f"target exists: {target.name}", code=409)
        path.rename(target)
        stem, dot, ext = new_name.rpartition(".")
        if row["is_dir"] or not dot or not stem:
            stem, ext = new_name, ""
        sync = getattr(library, "sync", None)
        emit = sync is not None and getattr(sync, "emit_messages", False)
        ops = []
        with db.transaction():
            db.update(FilePath, {"id": row["id"]},
                      {"name": stem, "extension": ext.lower()})
            if emit:
                ops.append(sync.shared_update(FilePath, row["pub_id"], "name", stem))
                ops.append(sync.shared_update(FilePath, row["pub_id"],
                                              "extension", ext.lower()))
            if row["is_dir"]:
                # rewrite descendants' materialized_path prefix in the same
                # transaction — later jobs resolve absolute paths from it.
                # SQL prefix match keeps the transaction O(descendants), not
                # O(location rows).
                old_prefix = f"{row['materialized_path'] or '/'}{row['name']}/"
                new_prefix = f"{row['materialized_path'] or '/'}{stem}/"
                like = (old_prefix.replace("\\", "\\\\")
                        .replace("%", "\\%").replace("_", "\\_")) + "%"
                children = db.query(
                    "SELECT id, pub_id, materialized_path FROM file_path "
                    "WHERE location_id = ? AND materialized_path LIKE ? ESCAPE '\\'",
                    (row["location_id"], like))
                for child in children:
                    new_mp = new_prefix + child["materialized_path"][len(old_prefix):]
                    db.update(FilePath, {"id": child["id"]},
                              {"materialized_path": new_mp})
                    if emit:
                        ops.append(sync.shared_update(
                            FilePath, child["pub_id"], "materialized_path", new_mp))
            if ops:
                sync.log_ops(ops)
        if ops:
            sync.created()
        invalidate_query(library, "search.paths")
        return None

    @router.library_mutation("files.createDirectory")
    def create_dir(node, library, arg):
        from ...objects.fs import location_path_of

        root = location_path_of(library.db, arg["location_id"])
        parent = root / arg.get("sub_path", "").strip("/")
        made = create_directory(parent, arg.get("name", "New Folder"))
        from ...locations import light_scan_location

        light_scan_location(library, arg["location_id"],
                            arg.get("sub_path", "").strip("/"))
        invalidate_query(library, "search.paths")
        return str(made)

    @router.library_mutation("files.createFile")
    def create_file_(node, library, arg):
        from ...objects.fs import location_path_of

        root = location_path_of(library.db, arg["location_id"])
        parent = root / arg.get("sub_path", "").strip("/")
        made = create_file(parent, arg.get("name", "New File"))
        from ...locations import light_scan_location

        light_scan_location(library, arg["location_id"],
                            arg.get("sub_path", "").strip("/"))
        invalidate_query(library, "search.paths")
        return str(made)

    # -- job launchers ------------------------------------------------------
    @router.library_mutation("files.copyFiles")
    def copy_files(node, library, arg):
        return node.jobs.spawn(library, [FileCopierJob({
            "sources": arg["sources"],
            "target_location_id": arg["target_location_id"],
            "target_dir": arg.get("target_dir", "")})])

    @router.library_mutation("files.cutFiles")
    def cut_files(node, library, arg):
        return node.jobs.spawn(library, [FileCutterJob({
            "sources": arg["sources"],
            "target_location_id": arg["target_location_id"],
            "target_dir": arg.get("target_dir", "")})])

    @router.library_mutation("files.duplicateFiles")
    def duplicate_files(node, library, arg):
        """Copy into the source's own directory (collision-safe naming)."""
        db = library.db
        jobs = []
        for fp_id in arg["sources"]:
            row, _path = file_path_abs(db, fp_id)
            jobs.append(FileCopierJob({
                "sources": [fp_id],
                "target_location_id": row["location_id"],
                "target_dir": (row["materialized_path"] or "/").strip("/")}))
        return node.jobs.spawn(library, jobs)

    @router.library_mutation("files.deleteFiles")
    def delete_files(node, library, arg):
        return node.jobs.spawn(library, [FileDeleterJob({"sources": arg["sources"]})])

    @router.library_mutation("files.eraseFiles")
    def erase_files(node, library, arg):
        return node.jobs.spawn(library, [FileEraserJob({
            "sources": arg["sources"], "passes": arg.get("passes", 2)})])

    @router.library_mutation("files.encryptFiles")
    def encrypt_files(node, library, arg):
        """api/files.rs encryptFiles → FileEncryptorJob (fs/encrypt.rs)."""
        return node.jobs.spawn(library, [FileEncryptorJob({
            "sources": arg["sources"],
            "password": arg.get("password"),
            "key_uuid": arg.get("key_uuid"),
            "algorithm": arg.get("algorithm", "XChaCha20Poly1305"),
            "metadata": arg.get("metadata", False),
            "erase_original": arg.get("erase_original", False)})])

    @router.library_mutation("files.decryptFiles")
    def decrypt_files(node, library, arg):
        """api/files.rs decryptFiles → FileDecryptorJob (fs/decrypt.rs)."""
        return node.jobs.spawn(library, [FileDecryptorJob({
            "sources": arg["sources"],
            "password": arg.get("password"),
            "key_uuid": arg.get("key_uuid"),
            "erase_original": arg.get("erase_original", False)})])


def _sync_update(library, obj: dict, field: str, value) -> None:
    sync = getattr(library, "sync", None)
    if sync is not None and getattr(sync, "emit_messages", False):
        sync.write_ops([sync.shared_update(Object, obj["pub_id"], field, value)])
