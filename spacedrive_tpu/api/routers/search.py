"""search.* procedures (api/search.rs): paths, pathsCount, objects,
objectsCount, ephemeralPaths — filterable, ordered, cursor-paginated.

Filter/ordering surface follows the reference's search args (:42-70 ordering
enums, :191-259 cursor types): locationId, search (name substring),
extensions, kinds, tags, favorite, hidden, dateRange; orderBy name|
sizeInBytes|dateCreated|dateModified + direction; cursor = last row id.
"""

from __future__ import annotations

import time
from typing import Any

from ... import telemetry
from ...locations.non_indexed import walk_ephemeral
from ...models import FilePath, Object
from ...telemetry import spans as _tspans
from ..router import ApiError


def _request_trace():
    """The ambient request trace when this handler runs under observed
    rspc dispatch (telemetry/requests.py) — lets the serialize phase
    show up in the slow-request ring next to the db.query spans; None
    (a bare timer) in any other context."""
    trace = _tspans.current_trace()
    return trace if getattr(trace, "record_db_spans", False) else None

_PATH_ORDERS = {"name", "size_in_bytes", "date_created", "date_modified"}


def _path_filters(arg: dict[str, Any]) -> tuple[str, list[Any], bool]:
    """(where-sql, params, needs_object_join) — the flag is True when any
    predicate references the ``o`` alias, so COUNT-shaped callers can
    drop the LEFT JOIN without duplicating filter knowledge here."""
    where, params = ["1=1"], []
    if arg.get("location_id") is not None:
        where.append("fp.location_id = ?")
        params.append(arg["location_id"])
    if arg.get("search"):
        where.append("fp.name LIKE ?")
        params.append(f"%{arg['search']}%")
    if arg.get("extensions"):
        marks = ",".join("?" for _ in arg["extensions"])
        where.append(f"fp.extension IN ({marks})")
        params.extend(e.lstrip(".").lower() for e in arg["extensions"])
    if arg.get("kinds"):
        marks = ",".join("?" for _ in arg["kinds"])
        where.append(f"o.kind IN ({marks})")
        params.extend(arg["kinds"])
    if arg.get("tags"):
        marks = ",".join("?" for _ in arg["tags"])
        where.append(f"fp.object_id IN (SELECT object_id FROM tag_on_object "
                     f"WHERE tag_id IN ({marks}))")
        params.extend(arg["tags"])
    if arg.get("favorite") is not None:
        where.append("o.favorite = ?")
        params.append(int(arg["favorite"]))
    if not arg.get("include_hidden"):
        where.append("(fp.hidden IS NULL OR fp.hidden = 0)")
    if arg.get("materialized_path"):
        where.append("fp.materialized_path = ?")
        params.append(arg["materialized_path"])
    if arg.get("date_range"):
        # [lo, hi], either side None; TEXT comparison under BINARY
        # collation (ISO-8601 with 'T' — lexicographic == chronological)
        lo, hi = arg["date_range"]
        if lo is not None:
            where.append("fp.date_created >= ?")
            params.append(lo)
        if hi is not None:
            where.append("fp.date_created <= ?")
            params.append(hi)
    if arg.get("size_range"):
        lo, hi = arg["size_range"]
        if lo is not None:
            where.append("fp.size_in_bytes >= ?")
            params.append(lo)
        if hi is not None:
            where.append("fp.size_in_bytes <= ?")
            params.append(hi)
    needs_object = any("o." in clause for clause in where)
    return " AND ".join(where), params, needs_object


def _engine(node):
    """The device search engine when armed (SD_SEARCH_ENGINE=device);
    None on the default SQL path and inside serve-pool workers."""
    return getattr(node, "search_engine", None)


def _ids_clause(ids) -> str:
    """The hydration WHERE for an engine-provided candidate set: the ids
    are our own int64 row ids, inlined (a 20k-id IN list stays far under
    SQLite's statement limits and parses in ~a millisecond)."""
    if len(ids) == 0:
        return "0=1"
    return f"fp.id IN ({','.join(str(int(i)) for i in ids)})"


#: NULL-safe order expressions (keyset cursors need total order)
_COALESCED = {
    "name": "COALESCE(fp.name, '')",
    "size_in_bytes": "COALESCE(fp.size_in_bytes, -1)",
    "date_created": "COALESCE(fp.date_created, '')",
    "date_modified": "COALESCE(fp.date_modified, '')",
}


def _order_parts(arg: dict[str, Any]) -> tuple[str, str, bool]:
    field = arg.get("order_by") or "name"
    if field not in _PATH_ORDERS:
        field = "name"
    desc = bool(arg.get("order_desc"))
    expr = _COALESCED[field]
    return expr, f"{expr} {'DESC' if desc else 'ASC'}, fp.id ASC", desc


def _cursor_sql(expr: str, desc: bool) -> str:
    """Keyset condition over (order value, id) — a bare id cursor would be
    incoherent under non-id orderings (cursor types, api/search.rs:191-259)."""
    cmp = "<" if desc else ">"
    return f"({expr} {cmp} ? OR ({expr} = ? AND fp.id > ?))"


def mount(router) -> None:
    @router.library_query("search.paths", pool=True)
    def paths(node, library, arg):
        """Cursor-paginated file_path search with object join."""
        arg = arg or {}
        where, params, _needs_o = _path_filters(arg)  # paths always joins
        take = min(int(arg.get("take", 100)), 500)
        expr, order_sql, desc = _order_parts(arg)
        cursor = arg.get("cursor")
        if arg.get("dirs_first"):
            # folders lead (the explorer's browse order); offset-mode only —
            # the keyset cursor doesn't encode the two-level order
            if cursor is not None:
                raise ApiError("dirs_first cannot combine with a cursor")
            order_sql = f"fp.is_dir DESC, {order_sql}"
        # device query engine (ISSUE 15): the columnar index scores the
        # FILTER predicates and returns the exact matching id set; the
        # SELECT below then reproduces ORDER BY/LIMIT/cursor semantics
        # byte-for-byte over `fp.id IN (...)`. None = serve SQL (engine
        # off, index stale/refreshing, ineligible predicate, oversized
        # candidate set) — SQLite stays the oracle.
        engine = _engine(node)
        t0 = time.perf_counter()
        cand = engine.candidate_ids(library, arg) \
            if engine is not None else None
        if cand is not None:
            where, params = _ids_clause(cand), []
        cursor_sql = ""
        if cursor is not None:
            value, last_id = cursor
            cursor_sql = f"AND {_cursor_sql(expr, desc)}"
            params = params + [value, value, last_id]
        # `skip`: offset pagination for the explorer's windowed grid —
        # random scroll positions need random access, which a cursor chain
        # cannot give; cursor stays the API for sequential consumers
        offset_sql = ""
        if cursor is None and arg.get("skip"):
            offset_sql = " OFFSET ?"
        rows = library.db.query(
            f"SELECT fp.*, o.pub_id AS object_pub_id, o.kind AS object_kind, "
            f"o.favorite AS favorite, o.note AS note, {expr} AS _order_val "
            f"FROM file_path fp LEFT JOIN object o ON fp.object_id = o.id "
            f"WHERE {where} {cursor_sql} ORDER BY {order_sql} LIMIT ?"
            f"{offset_sql}",
            params + [take + 1] + ([int(arg["skip"])] if offset_sql else []))
        items = []
        with telemetry.span(_request_trace(), "search.serialize",
                            rows=len(rows)):
            for r in rows[:take]:
                d = dict(FilePath.decode_row(r) | {
                    "object_pub_id": r["object_pub_id"],
                    "object_kind": r["object_kind"],
                    "favorite": bool(r["favorite"]), "note": r["note"],
                })
                d.pop("_order_val", None)
                items.append(d)
        next_cursor = None
        if len(rows) > take and items:
            next_cursor = [rows[take - 1]["_order_val"], items[-1]["id"]]
        if engine is not None and cand is None:
            engine.note_sqlite_serve(time.perf_counter() - t0)
        return {"items": items, "cursor": next_cursor}

    @router.library_query("search.pathsCount", pool=True)
    def paths_count(node, library, arg):
        engine = _engine(node)
        t0 = time.perf_counter()
        if engine is not None:
            # the count is a pure mask sum on the columnar index — no SQL
            # at all when the index is fresh and the predicate eligible
            n = engine.count(library, arg or {})
            if n is not None:
                return n
        where, params, needs_object = _path_filters(arg or {})
        # without o.* predicates the COUNT runs index-only over the
        # (location_id, hidden) covering index instead of a rowid lookup
        # per file_path (the 9.6 s p99 ISSUE 11 names; the plan is
        # asserted in tests/test_models.py). COUNT semantics are
        # unchanged either way: the join is on object's PK, so it can
        # never duplicate rows.
        join = ("LEFT JOIN object o ON fp.object_id = o.id "
                if needs_object else "")
        n = library.db.query(
            f"SELECT COUNT(*) n FROM file_path fp {join}WHERE {where}",
            params)[0]["n"]
        if engine is not None:
            engine.note_sqlite_serve(time.perf_counter() - t0)
        return n

    @router.library_query("search.objects", pool=True)
    def objects(node, library, arg):
        arg = arg or {}
        where, params = ["1=1"], []
        if arg.get("kinds"):
            marks = ",".join("?" for _ in arg["kinds"])
            where.append(f"o.kind IN ({marks})")
            params.extend(arg["kinds"])
        if arg.get("favorite") is not None:
            where.append("o.favorite = ?")
            params.append(int(arg["favorite"]))
        if arg.get("tags"):
            marks = ",".join("?" for _ in arg["tags"])
            where.append(f"o.id IN (SELECT object_id FROM tag_on_object "
                         f"WHERE tag_id IN ({marks}))")
            params.extend(arg["tags"])
        take = min(int(arg.get("take", 100)), 500)
        cursor_sql = ""
        if arg.get("cursor") is not None:
            cursor_sql = "AND o.id > ?"
            params.append(arg["cursor"])
        rows = library.db.query(
            f"SELECT o.* FROM object o WHERE {' AND '.join(where)} {cursor_sql} "
            f"ORDER BY o.id LIMIT ?", params + [take + 1])
        items = [Object.decode_row(r) for r in rows[:take]]
        return {"items": items,
                "cursor": items[-1]["id"] if len(rows) > take else None}

    @router.library_query("search.objectsCount", pool=True)
    def objects_count(node, library, arg):
        return library.db.query("SELECT COUNT(*) n FROM object")[0]["n"]

    @router.query("search.ephemeralPaths")
    def ephemeral_paths(node, arg):
        """Non-indexed directory listing (api/search.rs:328 /
        location/non_indexed.rs)."""
        arg = arg or {}
        with_thumbs = bool(arg.get("with_thumbnails"))
        return walk_ephemeral(
            arg["path"],
            include_hidden=bool(arg.get("include_hidden")),
            # thumbnails are keyed by cas_id, so with_thumbnails implies it
            with_cas_ids=bool(arg.get("with_cas_ids")) or with_thumbs,
            # with_thumbnails: generate on-the-fly previews into the node's
            # cache (served at /spacedrive/thumbnail/...)
            node=node if with_thumbs else None)

    @router.library_query("search.duplicates", pool=True)
    def duplicates(node, library, arg):
        """Persisted near-duplicate pairs written by the chained
        dedup_detector job (near_duplicate table)."""
        arg = arg or {}
        where, params = "1=1", []
        if arg.get("location_id") is not None:
            where = "(fa.location_id = ? OR fb.location_id = ?)"
            params = [arg["location_id"], arg["location_id"]]
        limit = max(0, min(int(arg.get("take", 200)), 1000))
        rows = library.db.query(
            f"SELECT nd.id, nd.similarity, nd.date_detected, "
            f"fa.id AS a_id, fa.materialized_path AS a_dir, fa.name AS a_name, "
            f"fa.extension AS a_ext, fa.size_in_bytes AS a_size, "
            f"fb.id AS b_id, fb.materialized_path AS b_dir, fb.name AS b_name, "
            f"fb.extension AS b_ext, fb.size_in_bytes AS b_size "
            f"FROM near_duplicate nd "
            f"JOIN file_path fa ON nd.file_path_a_id = fa.id "
            f"JOIN file_path fb ON nd.file_path_b_id = fb.id "
            f"WHERE {where} ORDER BY nd.similarity DESC, nd.id LIMIT ?",
            params + [limit])
        return [dict(r) for r in rows]

    @router.library_query("search.chunkDuplicates", pool=True)
    def chunk_duplicates(node, library, arg):
        """Sub-file duplication from the chunk manifests (ISSUE 18): chunk
        hashes shared by more than one object — the inverted chunk-hash map
        the delta-transfer sender negotiates against, surfaced for the UI.
        One indexed GROUP BY over chunk_manifest; pure library.db reads, so
        it serves from the worker pool."""
        arg = arg or {}
        limit = max(0, min(int(arg.get("take", 200)), 1000))
        rows = library.db.query(
            "SELECT chunk_hash, COUNT(DISTINCT object_id) AS objects, "
            "COUNT(*) AS copies, MAX(length) AS length, "
            "SUM(length) - MAX(length) AS duplicated_bytes "
            "FROM chunk_manifest GROUP BY chunk_hash "
            "HAVING COUNT(DISTINCT object_id) > 1 "
            "ORDER BY duplicated_bytes DESC, chunk_hash LIMIT ?", [limit])
        return [dict(r) for r in rows]

    @router.library_query("search.nearDuplicates", pool=True)
    def near_duplicates(node, library, arg):
        """TPU MinHash similarity groups, served from the PERSISTED
        ``near_duplicate`` pairs the chained dedup_detector job wrote
        (ops/minhash.py computes them; this handler only reads). Pure
        library.db, so it serves from the worker pool and the replica
        tier — the live filesystem+device probe lives in the job, where
        compute belongs."""
        from ...objects.dedup import persisted_near_duplicate_groups

        arg = arg or {}
        return persisted_near_duplicate_groups(
            library.db, location_id=arg.get("location_id"),
            limit=int(arg.get("take", arg.get("limit", 1000))))
