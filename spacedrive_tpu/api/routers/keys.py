"""keys.* procedures — the key-manager surface.

Reference: core/src/api/keys.rs (24 procedures, shipped UNMOUNTED —
api/mod.rs:173 comments out `keys.mount()` because the keymanager is
disconnected upstream). Here the key manager works, so the core set is
mounted: setup/unlock/lock state, stored-key CRUD, mount/unmount.
"""

from __future__ import annotations

import functools

from ...crypto.keymanager import KeyManagerError
from ..router import ApiError


def _km(node):
    km = getattr(node, "key_manager", None)
    if km is None:
        raise ApiError("no key manager on this node")
    return km


def _translate(fn):
    """Locked/not-set-up/wrong-password are client errors, not server bugs."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except KeyManagerError as e:
            raise ApiError(str(e))

    return wrapper


def mount(router) -> None:
    @router.query("keys.isSetup")
    @_translate
    def is_setup(node, _arg=None):
        return _km(node).is_setup

    @router.query("keys.isUnlocked")
    @_translate
    def is_unlocked(node, _arg=None):
        return _km(node).is_unlocked

    @router.mutation("keys.setup")
    @_translate
    def setup(node, password: str):
        _km(node).setup(password)
        return True

    @router.mutation("keys.unlockKeyManager")
    @_translate
    def unlock(node, password: str):
        _km(node).unlock(password)
        return True

    @router.mutation("keys.lockKeyManager")
    @_translate
    def lock(node, _arg=None):
        _km(node).lock()
        return True

    @router.query("keys.list")
    @_translate
    def list_keys(node, _arg=None):
        return _km(node).list_keys()

    @router.mutation("keys.add")
    @_translate
    def add(node, arg):
        name = (arg or {}).get("name", "") if isinstance(arg, dict) else (arg or "")
        return _km(node).add_key(name)

    @router.mutation("keys.mount")
    @_translate
    def mount_key(node, key_uuid: str):
        _km(node).mount(key_uuid)
        return True

    @router.mutation("keys.unmount")
    @_translate
    def unmount_key(node, key_uuid: str):
        _km(node).unmount(key_uuid)
        return True

    @router.mutation("keys.deleteFromLibrary")
    @_translate
    def delete(node, key_uuid: str):
        _km(node).delete_key(key_uuid)
        return True
