"""keys.* procedures — the key-manager surface.

Reference: core/src/api/keys.rs (shipped UNMOUNTED — api/mod.rs:173
comments out `keys.mount()` because the keymanager is disconnected
upstream). Here the key manager works and the surface is mounted:
setup/unlock/lock/changeMasterPassword state, stored-key CRUD with
default-key + automount flags, mount/unmount/unmountAll/listMounted, and
keystore backup/restore. Not carried over: getSecretKey (the reference's
two-factor onboarding secret — our setup has no secret-key factor) and
syncKeyToLibrary (upstream's half-wired library key sync).
"""

from __future__ import annotations

import functools

from ...crypto.keymanager import KeyManagerError
from ..router import ApiError


#: procedures HTTP shells refuse while basic auth is off (any local user
#: can reach a localhost port): getKey RETURNS raw key material,
#: backupKeystore WRITES an arbitrary server-writable path, restoreKeystore
#: merges attacker-known key material into the keystore, and
#: enableAutoUnlock persists the root secret into the (weaker-than-argon2id)
#: keyring store — a silent at-rest downgrade if triggered by a stranger —
#: and disableAutoUnlock deletes the keyring-held root secret, a
#: feature-tamper that silently strips auto-unlock (availability, not
#: leakage, but still keystore security state a stranger shouldn't flip).
#: In-process consumers (client, FFI) are unaffected.
SECRET_PROCEDURES = frozenset({
    "keys.getKey", "keys.backupKeystore", "keys.restoreKeystore",
    "keys.enableAutoUnlock", "keys.disableAutoUnlock",
})


def _km(node):
    km = getattr(node, "key_manager", None)
    if km is None:
        raise ApiError("no key manager on this node")
    return km


def _translate(fn):
    """Locked/not-set-up/wrong-password are client errors, not server bugs."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except KeyManagerError as e:
            raise ApiError(str(e))

    return wrapper


def mount(router) -> None:
    @router.query("keys.isSetup")
    @_translate
    def is_setup(node, _arg=None):
        return _km(node).is_setup

    @router.query("keys.isUnlocked")
    @_translate
    def is_unlocked(node, _arg=None):
        return _km(node).is_unlocked

    @router.mutation("keys.setup")
    @_translate
    def setup(node, password: str):
        _km(node).setup(password)
        return True

    @router.mutation("keys.unlockKeyManager")
    @_translate
    def unlock(node, password: str):
        _km(node).unlock(password)
        return True

    @router.mutation("keys.lockKeyManager")
    @_translate
    def lock(node, _arg=None):
        _km(node).lock()
        return True

    @router.query("keys.list")
    @_translate
    def list_keys(node, _arg=None):
        return _km(node).list_keys()

    @router.mutation("keys.add")
    @_translate
    def add(node, arg):
        name = (arg or {}).get("name", "") if isinstance(arg, dict) else (arg or "")
        return _km(node).add_key(name)

    @router.mutation("keys.mount")
    @_translate
    def mount_key(node, key_uuid: str):
        _km(node).mount(key_uuid)
        return True

    @router.mutation("keys.unmount")
    @_translate
    def unmount_key(node, key_uuid: str):
        _km(node).unmount(key_uuid)
        return True

    @router.mutation("keys.deleteFromLibrary")
    @_translate
    def delete(node, key_uuid: str):
        _km(node).delete_key(key_uuid)
        return True

    @router.query("keys.listMounted")
    @_translate
    def list_mounted(node, _arg=None):
        return _km(node).list_mounted()

    @router.mutation("keys.unmountAll")
    @_translate
    def unmount_all(node, _arg=None):
        return _km(node).unmount_all()

    @router.query("keys.getDefault")
    @_translate
    def get_default(node, _arg=None):
        return _km(node).get_default()

    @router.mutation("keys.setDefault")
    @_translate
    def set_default(node, key_uuid: str):
        _km(node).set_default(key_uuid)
        return True

    @router.query("keys.getKey")
    @_translate
    def get_key(node, key_uuid: str):
        import base64

        return base64.b64encode(_km(node).get_key(key_uuid).expose()).decode()

    @router.mutation("keys.updateAutomountStatus")
    @_translate
    def update_automount(node, arg):
        _km(node).set_automount(arg["uuid"], bool(arg["status"]))
        return True

    @router.mutation("keys.changeMasterPassword")
    @_translate
    def change_master_password(node, arg):
        _km(node).change_master_password(arg["current"], arg["new"])
        return True

    @router.mutation("keys.clearMasterPassword")
    @_translate
    def clear_master_password(node, _arg=None):
        _km(node).clear_master_password()
        return True

    @router.query("keys.isKeyManagerUnlocking")
    @_translate
    def is_unlocking(node, _arg=None):
        return False  # unlock here is synchronous; never observably mid-flight

    @router.mutation("keys.enableAutoUnlock")
    @_translate
    def enable_auto_unlock(node, _arg=None):
        """Park the root secret in the OS keyring (kernel user-keyring, or
        the machine-bound encrypted file fallback) so this keystore
        auto-unlocks across restarts; returns the backend name."""
        return _km(node).enable_auto_unlock()

    @router.mutation("keys.disableAutoUnlock")
    @_translate
    def disable_auto_unlock(node, _arg=None):
        _km(node).disable_auto_unlock()
        return True

    @router.mutation("keys.backupKeystore")
    @_translate
    def backup_keystore(node, path: str):
        return _km(node).backup_keystore(path)

    @router.mutation("keys.restoreKeystore")
    @_translate
    def restore_keystore(node, arg):
        return _km(node).restore_keystore(arg["path"], arg["password"])
