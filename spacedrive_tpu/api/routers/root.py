"""Root procedures (api/mod.rs:105-167): buildInfo, nodeState,
toggleFeatureFlag."""

from __future__ import annotations

from ... import __version__


def mount(router) -> None:
    @router.query("buildInfo")
    def build_info(node, _arg):
        """Version + commit of the running core."""
        return {"version": __version__, "commit": "dev"}

    @router.query("nodeState")
    def node_state(node, _arg):
        """Node config + data dir + connected device inventory."""
        cfg = node.config.get()
        return {
            "id": cfg["id"], "name": cfg["name"],
            "data_path": str(node.data_dir),
            "p2p_port": cfg.get("p2p_port"),
            "features": cfg.get("features", []),
            "accelerator": cfg.get("accelerator"),
        }

    @router.subscription("invalidation.listen")
    def invalidation_listen(node, _arg):
        """Stream of invalidate_query events — the frontend cache-refresh
        feed (mount_invalidate, api/mod.rs:183)."""
        from ._util import filtered_subscription

        return filtered_subscription(node, {"invalidate_query"})

    @router.mutation("toggleFeatureFlag")
    def toggle_feature_flag(node, feature: str):
        """Flip a BackendFeature; returns the new enabled state."""
        enabled = node.config.toggle_feature(feature)
        from ...config import BackendFeature

        if feature == BackendFeature.SYNC_EMIT_MESSAGES:
            for library in node.libraries.list():
                library.sync.emit_messages = enabled
        node.emit("feature_flags", node.config.get().get("features", []))
        return enabled
