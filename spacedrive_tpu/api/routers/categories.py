"""categories.list (api/categories.rs): seeded overview categories with
object counts per kind."""

from __future__ import annotations

from ...objects.tags import CATEGORIES


def mount(router) -> None:
    @router.library_query("categories.list", pool=True)
    def list_categories(node, library, _arg):
        counts = {r["kind"]: r["n"] for r in library.db.query(
            "SELECT kind, COUNT(*) n FROM object GROUP BY kind")}
        from ...objects.kind import CATEGORY_KINDS

        out = []
        for name in CATEGORIES:
            kinds = CATEGORY_KINDS.get(name, ())
            out.append({"category": name, "kinds": list(kinds),
                        "count": sum(counts.get(k, 0) for k in kinds)})
        return out
