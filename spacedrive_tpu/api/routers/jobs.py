"""jobs.* procedures (api/jobs.rs): reports (grouped with children),
isActive, clear, clearAll, pause, resume, cancel, job launchers, progress +
newThumbnail subscriptions."""

from __future__ import annotations

from ...jobs import JobStatus
from ...models import JobRow
from ...objects.validator import ObjectValidatorJob
from ..invalidate import invalidate_query
from ._util import filtered_subscription

_ACTIVE = {JobStatus.QUEUED, JobStatus.RUNNING, JobStatus.PAUSED}


def mount(router) -> None:
    @router.library_query("jobs.reports")
    def reports(node, library, _arg):
        """All job reports, children grouped under their chain head
        (api/jobs.rs:67)."""
        from ...jobs.report import JobStatus

        rows = library.db.find(JobRow, order_by="date_created DESC")
        by_parent: dict[str | None, list] = {}
        for r in rows:
            r.pop("data", None)  # serialized state stays internal
            r["status_name"] = JobStatus.NAMES.get(r["status"], "?")
            by_parent.setdefault(r["parent_id"], []).append(r)
        out = []
        for head in by_parent.get(None, []):
            head["children"] = by_parent.get(head["id"], [])
            out.append(head)
        return out

    @router.query("jobs.isActive")
    def is_active(node, _arg):
        return node.jobs.is_active()

    @router.library_mutation("jobs.clear")
    def clear(node, library, job_id: str):
        library.db.delete(JobRow, {"id": job_id})
        invalidate_query(library, "jobs.reports")
        return None

    @router.library_mutation("jobs.clearAll")
    def clear_all(node, library, _arg):
        """Remove every non-active report (api clearAll)."""
        for row in library.db.find(JobRow):
            if row["status"] not in _ACTIVE:
                library.db.delete(JobRow, {"id": row["id"]})
        invalidate_query(library, "jobs.reports")
        return None

    @router.mutation("jobs.pause")
    def pause(node, job_id: str):
        return node.jobs.pause(job_id)

    @router.library_mutation("jobs.resume")
    def resume(node, library, job_id: str):
        return node.jobs.resume(library, job_id)

    @router.mutation("jobs.cancel")
    def cancel(node, job_id: str):
        return node.jobs.cancel(job_id)

    @router.library_mutation("jobs.objectValidator")
    def object_validator(node, library, arg):
        return node.jobs.spawn(library, [ObjectValidatorJob({
            "location_id": arg["location_id"],
            "sub_path": arg.get("sub_path"),
            "revalidate": arg.get("revalidate", False)})])

    @router.library_mutation("jobs.identifyUniqueFiles")
    def identify_unique_files(node, library, arg):
        from ...objects.file_identifier import FileIdentifierJob

        return node.jobs.spawn(library, [FileIdentifierJob({
            "location_id": arg["location_id"],
            "sub_path": arg.get("sub_path")})])

    @router.library_mutation("jobs.generateThumbsForLocation")
    def generate_thumbs(node, library, arg):
        from ...objects.media.processor import MediaProcessorJob

        return node.jobs.spawn(library, [MediaProcessorJob({
            "location_id": arg["location_id"],
            "sub_path": arg.get("sub_path"),
            "regenerate": arg.get("regenerate", False)})])

    @router.library_subscription("jobs.progress")
    def progress(node, library, _arg):
        """JobProgress events for this library (api/jobs.rs:33)."""
        return filtered_subscription(node, {"job_progress"}, library.id)

    @router.library_subscription("jobs.newThumbnail")
    def new_thumbnail(node, library, _arg):
        return filtered_subscription(node, {"new_thumbnail"}, library.id)
