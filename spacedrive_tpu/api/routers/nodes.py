"""nodes.* procedures (api/nodes.rs): edit, listLocations."""

from __future__ import annotations

from ...models import Location


def mount(router) -> None:
    @router.mutation("nodes.edit")
    def edit(node, arg):
        updates = {}
        if arg.get("name"):
            updates["name"] = arg["name"]
        if updates:
            node.config.write(**updates)
        return None

    @router.library_query("nodes.listLocations", pool=True)
    def list_locations(node, library, _arg):
        return library.db.find(Location, order_by="name")
