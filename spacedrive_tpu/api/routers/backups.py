"""backups.* procedures (api/backups.rs): getAll, backup, restore, delete."""

from __future__ import annotations

from ...backups import delete_backup, do_backup, do_restore, list_backups


def mount(router) -> None:
    @router.query("backups.getAll")
    def get_all(node, _arg):
        return {"backups": list_backups(node),
                "directory": str(node.data_dir / "backups")}

    @router.mutation("backups.backup")
    def backup(node, library_id: str):
        return do_backup(node, library_id)

    @router.mutation("backups.restore")
    def restore(node, backup_path: str):
        return do_restore(node, backup_path)

    @router.mutation("backups.delete")
    def delete(node, backup_id: str):
        delete_backup(node, backup_id)
        return None
