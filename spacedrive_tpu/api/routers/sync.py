"""sync.* procedures (api/sync.rs): messages + newMessage subscription."""

from __future__ import annotations

from ._util import filtered_subscription


def mount(router) -> None:
    @router.library_query("sync.messages")
    def messages(node, library, arg):
        """Raw op-log feed for the sync debug page."""
        arg = arg or {}
        ops, has_more = library.sync.get_ops(arg.get("clocks"),
                                             int(arg.get("count", 100)))
        return {"ops": ops, "has_more": has_more}

    @router.library_subscription("sync.newMessage")
    def new_message(node, library, _arg):
        return filtered_subscription(node, {"sync.newMessage"}, library.id)
