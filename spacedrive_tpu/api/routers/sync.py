"""sync.* procedures (api/sync.rs): messages + newMessage subscription."""

from __future__ import annotations

from ._util import filtered_subscription


def mount(router) -> None:
    @router.library_query("sync.messages")
    def messages(node, library, arg):
        """Raw op-log feed for the sync debug page."""
        arg = arg or {}
        ops, has_more = library.sync.get_ops(arg.get("clocks"),
                                             int(arg.get("count", 100)))
        return {"ops": ops, "has_more": has_more}

    @router.library_subscription("sync.newMessage")
    def new_message(node, library, _arg):
        return filtered_subscription(node, {"sync.newMessage"}, library.id)

    @router.query("sync.fleetStatus")
    def fleet_status(node, _arg):
        """The fleet-survival surface (ISSUE 8): the node-wide ingest
        admission budget (ops/bytes in flight vs configured bounds, shed
        totals) and, per loaded library, the partitioned ingest-lane pool
        (lane count, bounded queue depths) when one is active."""
        budget = getattr(node, "ingest_budget", None)
        libraries = {}
        for library in node.libraries.list():
            pool = library.__dict__.get("_ingest_lanes")
            if pool is not None:
                libraries[library.id] = pool.status()
        return {"budget": budget.status() if budget is not None else None,
                "libraries": libraries}
