"""tags.* procedures (api/tags.rs): list, get, getForObject, getWithObjects,
create, assign, update, delete."""

from __future__ import annotations

from ...models import Tag
from ...objects.tags import (assign_tag, create_tag, delete_tag,
                             objects_for_tag, tags_for_object, update_tag)
from ..router import ApiError


def mount(router) -> None:
    @router.library_query("tags.list", pool=True)
    def list_tags(node, library, _arg):
        return library.db.find(Tag, order_by="name")

    @router.library_query("tags.get", pool=True)
    def get(node, library, tag_id: int):
        row = library.db.find_one(Tag, {"id": tag_id})
        if row is None:
            raise ApiError("tag not found", code=404)
        return row

    @router.library_query("tags.getForObject", pool=True)
    def get_for_object(node, library, object_id: int):
        return tags_for_object(library, object_id)

    @router.library_query("tags.getWithObjects", pool=True)
    def get_with_objects(node, library, tag_id: int):
        return {"tag": library.db.find_one(Tag, {"id": tag_id}),
                "objects": objects_for_tag(library, tag_id)}

    @router.library_mutation("tags.create")
    def create(node, library, arg):
        return create_tag(library, arg["name"], arg.get("color"))

    @router.library_mutation("tags.assign")
    def assign(node, library, arg):
        assign_tag(library, arg["tag_id"], arg["object_ids"],
                   unassign=arg.get("unassign", False))
        return None

    @router.library_mutation("tags.update")
    def update(node, library, arg):
        update_tag(library, arg["id"], name=arg.get("name"),
                   color=arg.get("color"))
        return None

    @router.library_mutation("tags.delete")
    def delete(node, library, tag_id: int):
        delete_tag(library, tag_id)
        return None
