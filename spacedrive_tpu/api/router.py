"""Typed procedure router — the rspc equivalent.

Parity with core/src/api/mod.rs: a Node-scoped router of queries, mutations
and subscriptions, merged from per-domain sub-router modules (17 in the
reference, ~150 procedures); library-scoped procedures resolve their Library
from a LibraryArgs envelope via middleware (api/utils/library.rs:50); and
mount() validates every invalidation key domain code emits against the
registered queries — the reference's load-bearing `InvalidRequests::validate`
trick (api/utils/invalidate.rs:82-117) that keeps the frontend cache-
invalidation contract honest.

Transports (HTTP/WebSocket server shell, in-process tests, FFI) call
``resolve``/``subscribe`` with plain JSON-safe values; ``schema()`` exports
the procedure inventory the way the reference's bindings-codegen test does
(api/mod.rs:205-212).
"""

from __future__ import annotations

import dataclasses
import inspect
import logging
from typing import TYPE_CHECKING, Any, Callable

from .. import faults
from ..telemetry import requests as _requests
from ..telemetry import slo as _slo

if TYPE_CHECKING:
    from ..events import Subscription
    from ..library import Library
    from ..node import Node

logger = logging.getLogger(__name__)

QUERY = "query"
MUTATION = "mutation"
SUBSCRIPTION = "subscription"


class ApiError(Exception):
    def __init__(self, message: str, code: int = 400) -> None:
        super().__init__(message)
        self.code = code


class BusyError(ApiError):
    """429: admission control shed this dispatch (ISSUE 20). Carries the
    pressure-scaled ``retry_after_ms`` the client should back off for.
    Request telemetry classifies this (by type name) as outcome ``shed``
    — deliberate load management, excluded from SLO error ratios."""

    def __init__(self, message: str, retry_after_ms: int = 0) -> None:
        super().__init__(message, code=429)
        self.retry_after_ms = int(retry_after_ms)


class RawJson:
    """A query result that is ALREADY serialized to wire JSON.

    Serve-pool workers encode their reply once (``json.dumps(result,
    default=str)`` — the exact encoder ``Response.json`` uses) and ship
    the bytes; the shell splices them straight into the HTTP envelope
    instead of decode-in-node + re-encode-in-shell. Callers that want
    the structured value use ``Router.resolve(raw=False)`` (the default),
    which decodes transparently — only the shell opts into passthrough."""

    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        self.data = data

    def decode(self) -> Any:
        import json

        return json.loads(self.data)


@dataclasses.dataclass
class Procedure:
    key: str
    kind: str            # query | mutation | subscription
    scope: str           # node | library
    fn: Callable
    doc: str = ""
    #: pool-eligible (ISSUE 11): a PURE reader — touches nothing but
    #: ``library.db`` / ``node.libraries`` / ``node.data_dir``, so its
    #: dispatch may run in a serve-pool worker process against that
    #: process's read-only SQLite connection. The sdlint ``worker-purity``
    #: pass statically enforces the contract on every marked handler.
    pool: bool = False
    #: replica-eligible (ISSUE 19): pool handlers default to serving from
    #: watermark-eligible remote peers too. ``replica=False`` keeps a pool
    #: handler local-only — for pure-but-DIVERGENT reads (node.data_dir
    #: disk stats, volume rows) whose answer is node-specific even when
    #: every peer is converged. The sdlint ``replica-purity`` pass enforces
    #: the stricter no-divergent-state contract on the eligible set.
    replica: bool = True


class Router:
    def __init__(self, node: "Node") -> None:
        self.node = node
        self.procedures: dict[str, Procedure] = {}

    # -- registration -------------------------------------------------------
    def _register(self, key: str, kind: str, scope: str, fn: Callable,
                  pool: bool = False, replica: bool = True) -> Callable:
        if key in self.procedures:
            raise ValueError(f"duplicate procedure key {key!r}")
        if pool and (kind != QUERY or scope != "library"):
            # node-scoped results have no library to key the worker page
            # cache on — watermark bumps are strictly per-library, so a
            # cached node-scope response could never be invalidated
            raise ValueError(f"{key}: only library-scoped queries may be "
                             f"pool-dispatched")
        if not pool and not replica:
            raise ValueError(f"{key}: replica=False is only meaningful on "
                             f"pool-dispatched queries")
        self.procedures[key] = Procedure(key, kind, scope, fn,
                                         inspect.getdoc(fn) or "", pool=pool,
                                         replica=replica)
        return fn

    def query(self, key: str, scope: str = "node", pool: bool = False,
              replica: bool = True):
        return lambda fn: self._register(key, QUERY, scope, fn, pool=pool,
                                         replica=replica)

    def mutation(self, key: str, scope: str = "node"):
        return lambda fn: self._register(key, MUTATION, scope, fn)

    def subscription(self, key: str, scope: str = "node"):
        return lambda fn: self._register(key, SUBSCRIPTION, scope, fn)

    # library-scoped sugar
    def library_query(self, key: str, pool: bool = False,
                      replica: bool = True):
        return self.query(key, scope="library", pool=pool, replica=replica)

    def library_mutation(self, key: str):
        return self.mutation(key, scope="library")

    def library_subscription(self, key: str):
        return self.subscription(key, scope="library")

    # -- resolution ---------------------------------------------------------
    def _proc(self, key: str) -> Procedure:
        proc = self.procedures.get(key)
        if proc is None:
            raise ApiError(f"unknown procedure {key!r}", code=404)
        return proc

    def _library(self, library_id: str | None) -> "Library":
        if not library_id:
            raise ApiError("library_id required for library-scoped procedure")
        try:
            return self.node.libraries.get(library_id)
        except KeyError:
            raise ApiError(f"library {library_id!r} not loaded", code=404) from None

    def resolve(self, key: str, arg: Any = None, library_id: str | None = None,
                *, raw: bool = False) -> Any:
        """Execute a query or mutation under per-procedure request
        telemetry (ISSUE 10: ``sd_rspc_*`` families + the slow-request
        ring). Library-scoped procedures receive (node, library, arg);
        node-scoped (node, arg).

        Pool-marked queries (ISSUE 11) dispatch to the multi-process
        reader pool when one is running: the worker resolves the same
        handler against its own read-only SQLite connection, so heavy
        read traffic escapes this process's GIL and writer-lock
        pressure. Any pool failure (no pool, worker crash, saturation)
        fails over to the in-process path below — queries are read-only,
        so re-running one is always safe.

        A pool worker replies with pre-encoded wire bytes
        (:class:`RawJson`); ``raw=True`` passes them through for the
        shell to splice, anything else gets the decoded value."""
        proc = self._proc(key)
        if proc.kind == SUBSCRIPTION:
            raise ApiError(f"{key} is a subscription; use subscribe()")
        # bounded tenant class for per-tenant telemetry + fair-share
        # admission (ISSUE 20): an 8-hex library-id hash, "local" for
        # node-scoped dispatches
        tenant = _slo.tenant_label(library_id)

        def dispatch() -> Any:
            # latency/failure chaos for the serving tier (`rspc:stall`,
            # `rspc:eio`, ...) — inside the observed scope so injected
            # slowness lands in the histograms and the slow ring exactly
            # like organic slowness
            faults.inject("rspc", key=key)
            # admission at dispatch (ISSUE 20): the IngestBudget shape
            # applied to the serving tier — shed with an explicit 429 +
            # retry-after instead of queueing unboundedly. telemetry.*
            # stays exempt: observability must survive the overload it
            # exists to narrate.
            admission = None
            budget = getattr(self.node, "dispatch_budget", None)
            if budget is not None and not key.startswith("telemetry."):
                from ..sync.admission import Busy

                verdict = budget.try_admit(tenant)
                if isinstance(verdict, Busy):
                    raise BusyError(
                        f"{key}: {verdict.reason}; retry after "
                        f"{verdict.retry_after_ms} ms",
                        retry_after_ms=verdict.retry_after_ms)
                admission = verdict
            try:
                return _dispatch_admitted()
            finally:
                if admission is not None:
                    admission.release()

        def _dispatch_admitted() -> Any:
            if proc.scope == "library":
                library = self._library(library_id)
            pool = getattr(self.node, "reader_pool", None)
            engine_local = False
            if proc.pool:
                # device search engine (ISSUE 15): when the in-process
                # handler would serve this query from the device-resident
                # index, skip the pool AND the replica tier — workers and
                # peers have no index, and the engine beats both (else it
                # wouldn't be armed). Stale/ineligible dispatches keep
                # pooling.
                engine = getattr(self.node, "search_engine", None)
                engine_local = (engine is not None
                                and engine.prefers_inprocess(
                                    proc.key, library_id, arg))
            # distributed replica rung (ISSUE 19): the TOP of the strict
            # degradation ladder replica → local reader pool → in-process.
            # The ReplicaRouter only ever returns a page a watermark-
            # eligible peer served (byte-identical encoder to the pool
            # path); any miss — no peers, ineligible, busy, transport
            # failure — returns None and the local rungs below take over,
            # accounted in sd_replica_failovers_total.
            replicas = getattr(self.node, "replica_router", None)
            if proc.pool and proc.replica and not engine_local \
                    and replicas is not None:
                served = replicas.dispatch(proc.key, arg, library_id)
                if served is not None:
                    return served
            if proc.pool and not engine_local and pool is not None:
                from ..server.pool import PoolUnavailable

                try:
                    return pool.dispatch(proc.key, arg, library_id)
                except PoolUnavailable:
                    pass  # counted by the pool; serve in-process below
            if proc.scope == "library":
                return proc.fn(self.node, library, arg)
            return proc.fn(self.node, arg)

        result = _requests.observed(key, proc.kind, dispatch, tenant=tenant)
        if isinstance(result, RawJson) and not raw:
            return result.decode()
        return result

    def subscribe(self, key: str, arg: Any = None,
                  library_id: str | None = None) -> "Subscription":
        proc = self._proc(key)
        if proc.kind != SUBSCRIPTION:
            raise ApiError(f"{key} is not a subscription")

        def dispatch() -> Any:
            # counts the subscription SETUP (the stream itself is pumped
            # by the transport; its lifetime is not a request)
            if proc.scope == "library":
                return proc.fn(self.node, self._library(library_id), arg)
            return proc.fn(self.node, arg)

        return _requests.observed(key, proc.kind, dispatch)

    # -- schema export (bindings-codegen analogue) -------------------------
    def schema(self) -> dict[str, Any]:
        return {
            "version": 1,
            "procedures": [
                {"key": p.key, "kind": p.kind, "scope": p.scope, "doc": p.doc}
                for p in sorted(self.procedures.values(), key=lambda p: p.key)
            ],
        }


def mount(node: "Node") -> Router:
    """Build the full router (api::mount, mod.rs:102-203) and validate the
    invalidation-key contract."""
    from . import invalidate
    from .routers import (backups, categories, collections, files, jobs,
                          keys, libraries, locations, nodes, notifications,
                          p2p, preferences, root, search, sync, tags,
                          telemetry, volumes)

    router = Router(node)
    for module in (root, libraries, locations, search, files, jobs, tags,
                   volumes, nodes, notifications, preferences, backups,
                   categories, sync, p2p, keys, collections, telemetry):
        module.mount(router)
    invalidate.validate(router)
    # typed-client contract: every key in api/types.py must exist (the
    # generated client/core.ts can then never name a ghost procedure)
    from . import types as ts_types

    ts_types.validate(router)
    return router
