"""Invalidation-key registry + mount-time validation.

The reference's `invalidate_query!` macro records every invocation in a
global and validates each recorded key (and argument type) against the router
when `api::mount` runs in debug builds (api/utils/invalidate.rs:24-117) — a
compile-adjacent guarantee that the frontend's cache invalidation never
references a procedure that doesn't exist. Python has no macro collection
step, so the registry is explicit: domain code calls ``invalidate_query``
(or is listed in USED_KEYS if it emits the raw event), and ``validate``
cross-checks the union against the mounted router's query keys at startup.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

logger = logging.getLogger(__name__)

if TYPE_CHECKING:
    from ..library import Library
    from .router import Router

#: keys emitted via raw ``library.emit("invalidate_query", ...)`` calls in
#: domain code (grep-audited); new call sites must be added here or use
#: invalidate_query() below, which records automatically.
USED_KEYS: set[str] = {
    "search.paths",
    "search.objects",
    "locations.list",
    "tags.list",
    "tags.getForObject",
    "preferences.get",
    "jobs.reports",
    "notifications.get",
    "libraries.list",
    "search.duplicates",
}

_RUNTIME_KEYS: set[str] = set()


def invalidate_query(library: "Library", key: str, arg: Any = None) -> None:
    """Emit an invalidation event; records the key for mount validation."""
    _RUNTIME_KEYS.add(key)
    library.emit("invalidate_query", {"key": key, "arg": arg})


class InvalidationError(Exception):
    pass


def validate(router: "Router") -> None:
    """InvalidRequests::validate — every declared/used invalidation key must
    name a registered QUERY procedure."""
    from .router import QUERY

    queries = {p.key for p in router.procedures.values() if p.kind == QUERY}
    bad = sorted(k for k in (USED_KEYS | _RUNTIME_KEYS) if k not in queries)
    if bad:
        raise InvalidationError(
            f"invalidation keys with no matching query procedure: {bad}")
