"""TypeScript types for the API surface — the typed-client contract.

The reference's contract file is ``packages/client/src/core.ts``, GENERATED
from the Rust router by running an rspc/specta export test
(core/src/api/mod.rs:205-212) and consumed through the library/node scope
split in ``packages/client/src/rspc.tsx:13-43``. This framework has no
macro-derived types, so the contract lives here as one reviewed map:

- ``TS_PRELUDE``: the shared row interfaces (mirrors of models/schema.py
  rows as the routers serialize them — coarse where a router passes rows
  through verbatim, with an index-signature escape hatch).
- ``TYPES``: procedure key → (arg TS type, result TS type). Keys MUST
  exist on the mounted router — ``validate()`` runs at mount, exactly like
  the invalidation-key registry — so this map can never drift to naming
  procedures that don't exist. Procedures not listed here fall back to
  ``unknown`` in the generated client (still present in the key unions and
  the scope split, which is what the explorer consumes).

``python -m spacedrive_tpu.api.codegen`` renders this into
``client/core.ts`` (types) and ``client/procedures.js`` (runtime mirror
the web explorer loads); tests/test_ts_client.py is the golden gate.
"""

from __future__ import annotations

TS_PRELUDE = """\
/** Mirrors models/schema.py rows as the routers serialize them. Fields the
 * explorer relies on are typed; rows keep an escape hatch because several
 * routers pass DB rows through verbatim. */
export interface Library { id: string; name: string; [key: string]: unknown }
export interface LocationRow {
  id: number; pub_id: string; name: string | null; path: string | null;
  hasher: string | null; [key: string]: unknown
}
export interface FilePathRow {
  id: number; pub_id: string; name: string | null; extension: string | null;
  materialized_path: string | null; is_dir: boolean | number;
  cas_id: string | null; object_id: number | null;
  size_in_bytes: number | null; kind?: number | null; [key: string]: unknown
}
export interface ObjectRow {
  id: number; pub_id: string; kind: number | null; favorite?: boolean | null;
  note?: string | null; [key: string]: unknown
}
export interface TagRow {
  id: number; pub_id: string; name: string | null; color: string | null;
  [key: string]: unknown
}
export interface CollectionRow {
  id: number; pub_id: string; name: string | null; member_count?: number;
  [key: string]: unknown
}
export interface JobReport {
  id: string; name: string; status: string; task_count: number;
  completed_task_count: number; message?: string | null;
  children?: JobReport[]; [key: string]: unknown
}
export interface SearchPathsResult { items: FilePathRow[]; cursor: number | null }
export interface NodeState {
  id: string; name: string; data_path: string; [key: string]: unknown
}
export interface Statistics { [key: string]: unknown }
export interface PeerMetadata {
  identity: string; connected: boolean; [key: string]: unknown
}
export interface JobProgressEvent {
  id: string; status?: string; completed_task_count?: number;
  message?: string; [key: string]: unknown
}
/** One flight-recorder event (telemetry.watch / GET /telemetry/stream). */
export interface TelemetryEvent {
  seq: number; name: string; unix: number; [key: string]: unknown
}
/** An alert rule plus its live evaluator state (telemetry.alerts).
 * `value` is the CONFIGURED threshold; `live_value` the last observation
 * (null while the rule is healthy or has no matching series). */
export interface AlertRuleState {
  name: string; kind: string; series: string; op: string; value: number;
  for_s: number; window_s: number; severity: string; description: string;
  labels: Record<string, string>; firing: boolean; pending: boolean;
  live_value: number | null; [key: string]: unknown
}
/** Per-procedure serving stats (telemetry.requestStats). Quantiles are
 * histogram-bucket estimates; `errors` counts api_error + error
 * outcomes. */
export interface ProcedureRequestStats {
  count: number; total_s: number; mean_s: number;
  p50_s: number; p95_s: number; p99_s: number;
  errors?: number; bytes_in?: number; bytes_out?: number
}
/** One slow-request ring entry: the request plus its full span tree
 * (SQL / reader-wait / serialize breakdown of a slow search.paths). */
export interface SlowRequestEntry {
  proc: string; kind: string; outcome: string; duration_s: number;
  unix: number; tree: Record<string, unknown>
}
/** Multi-process reader-pool state (telemetry.requestStats.serve_pool);
 * null while the node serves in the degraded in-process mode. */
export interface ServePoolStatus {
  workers: number; min_workers: number; max_workers: number;
  alive: number; idle: number; enabled: boolean;
  running: boolean; restarts: number; resizes: number; failovers: number;
  cache_hits: number; cache_misses: number; watermarks: number;
  per_worker: Record<string, Record<string, number>>
}
/** One SLO objective with live state (telemetry.sloStatus). `burn` maps
 * window labels ("5m", "1h", ...) to burn-rate multiples of the
 * error-budget spend rate; `firing` the AND-gated fast/slow pair state. */
export interface SloObjectiveStatus {
  name: string; threshold_s: number; target: number; window_s: number;
  proc: string | null; tenant: string | null;
  fast_windows: number[]; slow_windows: number[];
  fast_burn: number; slow_burn: number; severity: string;
  description: string; sli: number | null; good: number; valid: number;
  budget_remaining: number; burn: Record<string, number>;
  firing: Record<string, boolean>
}
/** rspc dispatch-admission budget state (telemetry.sloStatus);
 * null when SD_RSPC_ADMISSION=0 turned the gate off. */
export interface DispatchAdmissionStatus {
  budget_inflight: number; in_flight: number; tenants_in_flight: number;
  shed: number
}
/** telemetry.sloStatus: SLO engine + admission state (ISSUE 20). */
export interface SloStatus {
  objectives: SloObjectiveStatus[];
  dispatch_admission: DispatchAdmissionStatus | null
}
/** telemetry.requestStats: the serving-tier observability surface. */
export interface RequestStats {
  enabled: boolean; in_flight: number; slow_threshold_ms: number;
  procedures: Record<string, ProcedureRequestStats>;
  slow: SlowRequestEntry[]; serve_pool: ServePoolStatus | null
}
/** The node-wide ingest admission budget (sync.fleetStatus). */
export interface IngestBudgetStatus {
  budget_ops: number; budget_bytes: number; ops_in_flight: number;
  bytes_in_flight: number; peers_in_flight: number; shed_windows: number;
  shed_ops: number
}
/** One library's partitioned ingest-lane pool (sync.fleetStatus). */
export interface IngestLaneStatus {
  lanes: number; queue_depths: number[]; queue_bound: number;
  windows: number; submissions: number
}
/** sync.fleetStatus: how the node is holding up under fleet load. */
export interface FleetStatus {
  budget: IngestBudgetStatus | null;
  libraries: Record<string, IngestLaneStatus>
}
"""

#: procedure key -> (arg TS type, result TS type); unlisted keys emit
#: ``unknown``. Keep entries alphabetical within their router block.
TYPES: dict[str, tuple[str, str]] = {
    # root
    "buildInfo": ("null", "{ version: string; commit: string }"),
    "nodeState": ("null", "NodeState"),
    # libraries
    "libraries.create": ("{ name: string }", "Library"),
    "libraries.delete": ("string", "null"),
    "libraries.edit": ("{ id: string; name?: string; description?: string }", "null"),
    "libraries.list": ("null", "Library[]"),
    "libraries.statistics": ("null", "Statistics"),
    # locations
    "locations.create": (
        "{ path: string; dry_run?: boolean; indexer_rules_ids?: number[] }",
        "LocationRow | null"),
    "locations.delete": ("number", "null"),
    "locations.fullRescan": ("{ location_id: number }", "string"),
    "locations.get": ("number", "LocationRow | null"),
    "locations.list": ("null", "LocationRow[]"),
    "locations.update": ("{ id: number; [key: string]: unknown }", "null"),
    "locations.indexer_rules.create": (
        "{ name: string; rules: Record<string, string[]> }", "number"),
    "locations.indexer_rules.delete": ("number", "null"),
    "locations.indexer_rules.get": ("number", "Record<string, unknown> | null"),
    "locations.indexer_rules.list": ("null", "Record<string, unknown>[]"),
    # search
    "search.ephemeralPaths": (
        "{ path: string; withHiddenFiles?: boolean }",
        "{ entries: FilePathRow[] }"),
    "search.objects": (
        "{ take?: number; tags?: number[]; kind?: number[] }",
        "{ items: ObjectRow[] }"),
    "search.paths": (
        "{ location_id?: number; path?: string; search?: string; "
        "take?: number; skip?: number; dirs_first?: boolean; "
        "cursor?: [unknown, number] | null; "
        "[key: string]: unknown }",
        "SearchPathsResult"),
    "search.pathsCount": ("{ location_id?: number; [key: string]: unknown }",
                          "number"),
    "search.duplicates": ("{ location_id?: number }",
                          "Record<string, unknown>[]"),
    # jobs
    "jobs.cancel": ("string", "null"),
    "jobs.clear": ("string", "null"),
    "jobs.clearAll": ("null", "null"),
    "jobs.pause": ("string", "null"),
    "jobs.progress": ("null", "JobProgressEvent"),
    "jobs.reports": ("null", "JobReport[]"),
    "jobs.resume": ("string", "null"),
    # files
    "files.deleteFiles": ("{ location_id: number; file_path_ids: number[] } | "
                          "Record<string, unknown>", "string"),
    "files.renameFile": ("{ file_path_id: number; new_name: string }", "null"),
    "files.setFavorite": ("{ object_id: number; favorite: boolean }", "null"),
    "files.setNote": ("{ object_id: number; note: string | null }", "null"),
    # tags
    "tags.assign": ("{ object_ids: number[]; tag_id: number; unassign?: boolean }",
                    "null"),
    "tags.create": ("{ name: string; color?: string }", "TagRow"),
    "tags.delete": ("number", "null"),
    "tags.get": ("number", "TagRow | null"),
    "tags.getForObject": ("number", "TagRow[]"),
    "tags.list": ("null", "TagRow[]"),
    "tags.update": ("{ id: number; name?: string; color?: string }", "null"),
    # collections
    "albums.addObjects": ("{ id: number; object_ids: number[] }", "number"),
    "albums.create": ("{ name: string; is_hidden?: boolean } | string",
                      "CollectionRow"),
    "albums.delete": ("number", "null"),
    "albums.list": ("null", "CollectionRow[]"),
    "albums.objects": ("number", "FilePathRow[]"),
    "albums.removeObjects": ("{ id: number; object_ids: number[] }", "number"),
    "albums.update": ("{ id: number; name?: string; is_hidden?: boolean }",
                      "null"),
    "spaces.addObjects": ("{ id: number; object_ids: number[] }", "number"),
    "spaces.create": ("{ name: string; description?: string } | string",
                      "CollectionRow"),
    "spaces.delete": ("number", "null"),
    "spaces.list": ("null", "CollectionRow[]"),
    "spaces.objects": ("number", "FilePathRow[]"),
    "spaces.removeObjects": ("{ id: number; object_ids: number[] }", "number"),
    "spaces.update": ("{ id: number; name?: string; description?: string }",
                      "null"),
    "labels.assign": ("{ name: string; object_ids: number[]; remove?: boolean }",
                      "number"),
    "labels.getForObject": ("number", "Record<string, unknown>[]"),
    "labels.list": ("null", "Record<string, unknown>[]"),
    # volumes / nodes / notifications
    "nodes.edit": ("{ name?: string }", "null"),
    "notifications.dismiss": ("number", "null"),
    "notifications.dismissAll": ("null", "null"),
    "notifications.get": ("null", "Record<string, unknown>[]"),
    "volumes.list": ("null", "Record<string, unknown>[]"),
    # p2p
    "p2p.events": ("null", "Record<string, unknown>"),
    "p2p.nlmState": ("null", "Record<string, unknown>"),
    "p2p.peers": ("null", "PeerMetadata[]"),
    # sync
    "sync.fleetStatus": ("null", "FleetStatus"),
    "sync.messages": ("null", "Record<string, unknown>[]"),
    # telemetry
    "telemetry.alerts": ("null", "{ rules: AlertRuleState[] }"),
    "telemetry.jobTrace": ("string | { job_id: string }",
                           "Record<string, unknown> | null"),
    "telemetry.requestStats": ("{ slow_limit?: number } | null",
                               "RequestStats"),
    "telemetry.sloStatus": ("null", "SloStatus"),
    "telemetry.snapshot": ("null", "Record<string, unknown>"),
    "telemetry.watch": ("null", "TelemetryEvent"),
}


def validate(router) -> None:
    """Every typed key must name a mounted procedure (mount-time gate, the
    invalidation-registry trick: the map cannot drift ahead of the API)."""
    unknown = sorted(set(TYPES) - set(router.procedures))
    if unknown:
        raise RuntimeError(
            f"api/types.py names procedures that do not exist: {unknown}")
