"""Library and Libraries manager.

A Library (core/src/library/library.rs:39-61) is one synced database: its own
SQLite file, sync manager, instance identity, and config sidecar. The Libraries
manager (library/manager/mod.rs:51-61) loads ``libraries/*.sdlibrary`` configs
plus sibling ``.db`` files at startup, creates/edits/deletes libraries, and
broadcasts load/edit/delete events that the location watchers, job cold-resume
and networked-library machinery subscribe to.
"""

from __future__ import annotations

import logging
import threading
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any, Callable

from .config import Platform
from .models import ALL_MODELS, Database, Instance, utc_now
from .utils.migrator import VersionedConfig

if TYPE_CHECKING:
    from .node import Node

logger = logging.getLogger(__name__)


class LibraryConfig(VersionedConfig):
    """The versioned ``<uuid>.sdlibrary`` sidecar (library/config.rs)."""

    VERSION = 1

    @classmethod
    def defaults(cls) -> dict[str, Any]:
        return {"name": "", "description": "", "instance_id": 0}


def validate_library_name(name: str) -> str:
    """LibraryName newtype validation (library/name.rs)."""
    name = name.strip()
    if not name:
        raise ValueError("library name cannot be empty")
    return name


class Library:
    def __init__(self, lib_id: str, config: LibraryConfig, db: Database,
                 node: "Node | None" = None) -> None:
        self.id = lib_id
        self.config = config
        self.db = db
        self.node = node
        self._lock = threading.RLock()
        self.instance_id: int = config.get("instance_id", 0)
        self.sync = None  # attached by sync.Manager (sync layer)

    @property
    def name(self) -> str:
        return self.config.get("name", "")

    def emit(self, kind: str, payload: Any = None) -> None:
        if self.node is not None:
            self.node.events.emit_kind(kind, payload, library_id=self.id)

    def instance(self) -> dict[str, Any] | None:
        return self.db.find_one(Instance, {"id": self.instance_id})

    def add_remote_instance(self, instance_row: dict[str, Any]) -> int:
        """Register a paired peer instance (the responder inserts the
        originator's Instance and vice versa; pairing proto + the reference
        sync test's hand-pairing, core/crates/sync/tests/lib.rs:66-99)."""
        row = {k: v for k, v in instance_row.items() if k != "id"}
        row.setdefault("last_seen", utc_now())
        row.setdefault("date_created", utc_now())
        existing = self.db.find_one(Instance, {"pub_id": row["pub_id"]})
        if existing is not None:
            return existing["id"]
        return self.db.insert(Instance, row)

    def close(self) -> None:
        remover = getattr(self, "orphan_remover", None)
        if remover is not None:
            remover.stop()
        pool = self.__dict__.pop("_ingest_lanes", None)
        if pool is not None:  # partitioned ingest lanes (sync/lanes.py)
            pool.close()
        self.db.close()


class LibraryManagerEvent:
    LOAD = "load"
    EDIT = "edit"
    DELETE = "delete"
    INSTANCES_MODIFIED = "instances_modified"


class _Subscriber:
    """One mpscrr-backed event subscriber: callback subscribers get a drain
    thread that runs the fn and acks; channel subscribers ack themselves."""

    ACK_TIMEOUT = 30.0

    def __init__(self, fn: Callable[[str, "Library"], None] | None,
                 sender=None) -> None:
        from .utils.mpscrr import channel

        if sender is not None:
            # channel-mode: the mpscrr Sender holds the receiver weakly, so
            # a dropped-unclosed receiver reads as ChannelClosed → eviction
            self._sender = sender
            return
        self._sender, receiver = channel()
        self._fn = fn

        def drain() -> None:
            for req in receiver:
                event, library = req.message
                try:
                    fn(event, library)
                except Exception:
                    logger.exception("library event subscriber failed (%s)",
                                     event)
                finally:
                    req.respond()

        threading.Thread(target=drain, daemon=True,
                         name="library-events").start()

    def deliver(self, event: str, library: "Library") -> bool:
        """Send + await ack. Returns False when the subscriber is gone
        (caller unsubscribes it)."""
        from .utils.mpscrr import ChannelClosed

        try:
            self._sender.send((event, library), timeout=self.ACK_TIMEOUT)
            return True
        except ChannelClosed:
            return False
        except TimeoutError:
            logger.error("library event subscriber did not ack %s within %ss",
                         event, self.ACK_TIMEOUT)
            return True


class Libraries:
    """Loads and owns every library under ``<data_dir>/libraries``."""

    def __init__(self, data_dir: str | Path, node: "Node | None" = None) -> None:
        self.dir = Path(data_dir) / "libraries"
        self.node = node
        self._lock = threading.RLock()
        self._libraries: dict[str, Library] = {}
        self._subscribers: list["_Subscriber"] = []

    # -- events (mpscrr ack'd broadcast, manager/mod.rs:42-48) ---------------
    def subscribe(self, fn: Callable[[str, Library], None]) -> None:
        """Register for (event, library) callbacks over an mpscrr channel:
        a drain thread runs the callback and acks, and ``_emit`` waits for
        every subscriber's ack so boot-ordering consumers (watchers, NLM,
        cold resume) have definitely processed Load before boot continues.
        Replays Load for already-loaded libraries."""
        sub = _Subscriber(fn)
        with self._lock:
            self._subscribers.append(sub)
            current = list(self._libraries.values())
        for lib in current:
            sub.deliver(LibraryManagerEvent.LOAD, lib)

    def subscribe_channel(self):
        """Raw mpscrr Receiver for consumers that drain themselves; each
        Request.message is (event, library) and must be respond()ed.
        close() the receiver to unsubscribe — a receiver that is simply
        garbage-collected is auto-evicted on the next emit (the mpscrr
        Sender only holds it weakly)."""
        from .utils.mpscrr import channel

        sender, receiver = channel()
        sub = _Subscriber(None, sender=sender)
        with self._lock:
            self._subscribers.append(sub)
        return receiver

    def _emit(self, event: str, library: Library) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for sub in subs:
            if not sub.deliver(event, library):
                with self._lock:
                    try:
                        self._subscribers.remove(sub)
                    except ValueError:
                        pass

    # -- lifecycle ----------------------------------------------------------
    def init(self) -> None:
        """Load all .sdlibrary configs; corrupt ones are skipped with a warning
        (manager/mod.rs:95-120)."""
        self.dir.mkdir(parents=True, exist_ok=True)
        # sweep temp files a killed atomic write / mid-restore extraction
        # stranded (utils/atomic discipline: the temp is the only debris a
        # crash can leave) — every artifact dir a writer targets: library
        # files, backups, the sharded thumbnail cache, trace exports
        from .utils.atomic import cleanup_stale_tmp

        cleanup_stale_tmp(self.dir)
        if self.node is not None:
            for artifact_dir in ("backups", "thumbnails"):
                cleanup_stale_tmp(self.node.data_dir / artifact_dir)
            from .telemetry.spans import traces_dir

            cleanup_stale_tmp(traces_dir(self.node.data_dir))
        for cfg_path in sorted(self.dir.glob("*.sdlibrary")):
            lib_id = cfg_path.stem
            try:
                self._load(lib_id)
            except Exception:
                logger.exception("skipping corrupt library %s", lib_id)

    def _load(self, lib_id: str) -> Library:
        # boot-time integrity gate (recovery.py): WAL recovery + PRAGMA
        # quick_check BEFORE the model layer opens the file; a corrupt DB
        # is quarantined and restored from the newest valid backup (or
        # recreated fresh) — a repair event, never a boot failure
        from .recovery import ensure_library_integrity

        ensure_library_integrity(
            self.dir, lib_id,
            backups_path=(self.node.data_dir / "backups"
                          if self.node is not None else None),
            node=self.node)
        config = LibraryConfig.load_and_migrate(self.dir / f"{lib_id}.sdlibrary")
        db = Database(self.dir / f"{lib_id}.db", ALL_MODELS)
        self._ensure_instance_row(config, db)
        library = Library(lib_id, config, db, self.node)
        self._attach_services(library)
        with self._lock:
            self._libraries[lib_id] = library
        self._emit(LibraryManagerEvent.LOAD, library)
        return library

    def _ensure_instance_row(self, config: "LibraryConfig",
                             db: Database) -> None:
        """A fresh-DB repair (or a vanished DB file) leaves the surviving
        config's ``instance_id`` pointing at an Instance row the empty DB
        does not have — sync and identity surfaces would then raise on
        first use. "Never a boot failure" includes first use: re-seed the
        row exactly like :meth:`create` does and repoint the config."""
        iid = config.get("instance_id", 0)
        if iid and db.find_one(Instance, {"id": iid}) is not None:
            return
        from .p2p.identity import Identity as _Identity
        from .p2p.identity import encode_identity as _enc

        node_cfg = self.node.config.get() if self.node else {}
        seed = node_cfg.get("keypair_seed")
        node_remote_identity = (
            _Identity.from_seed(seed).to_remote_identity().encode()
            if seed else None)
        instance_id = db.insert(Instance, {
            "pub_id": str(uuid.uuid4()),
            "identity": _enc(_Identity()),
            "node_remote_identity": node_remote_identity,
            "node_id": node_cfg.get("id", str(uuid.uuid4())),
            "node_name": node_cfg.get("name", "node"),
            "node_platform": node_cfg.get("platform", Platform.current()),
            "last_seen": utc_now(),
            "date_created": utc_now(),
        })
        config["instance_id"] = instance_id
        config.save()
        logger.warning("library %s had no instance row for its config "
                       "(fresh-DB repair?); re-seeded instance %d",
                       config.get("name", "?"), instance_id)

    def _attach_services(self, library: Library) -> None:
        from .config import BackendFeature
        from .objects.gc import OrphanRemoverActor
        from .sync.manager import SyncManager  # cycle-free local import

        library.sync = SyncManager(library)
        if self.node is not None:
            features = self.node.config.get().get("features", [])
            library.sync.emit_messages = BackendFeature.SYNC_EMIT_MESSAGES in features
        # per-library GC (library.rs holds the orphan remover on Library)
        library.orphan_remover = OrphanRemoverActor(library)

    def create(self, name: str, description: str = "",
               lib_id: str | None = None,
               instance_pub_id: str | None = None,
               instance_identity: str | None = None) -> Library:
        """Create a library + its own Instance row (create_with_uuid is the
        pairing path, library/manager create_with_uuid). The instance gets a
        fresh ed25519 identity unless pairing supplies one (the
        IdentityOrRemoteIdentity encoding, identity_or_remote_identity.rs:48)."""
        name = validate_library_name(name)
        lib_id = lib_id or str(uuid.uuid4())
        if lib_id in self._libraries:
            raise ValueError(f"library {lib_id} already exists")
        from .p2p.identity import Identity as _Identity
        from .p2p.identity import encode_identity as _enc

        if instance_identity is None:
            instance_identity = _enc(_Identity())
        node_cfg_early = self.node.config.get() if self.node else {}
        seed = node_cfg_early.get("keypair_seed")
        node_remote_identity = (
            _Identity.from_seed(seed).to_remote_identity().encode() if seed else None)
        self.dir.mkdir(parents=True, exist_ok=True)
        config = LibraryConfig.load_and_migrate(self.dir / f"{lib_id}.sdlibrary")
        config["name"] = name
        config["description"] = description
        db = Database(self.dir / f"{lib_id}.db", ALL_MODELS)
        node_cfg = self.node.config.get() if self.node else {}
        instance_id = db.insert(Instance, {
            "pub_id": instance_pub_id or str(uuid.uuid4()),
            "identity": instance_identity,
            "node_remote_identity": node_remote_identity,
            "node_id": node_cfg.get("id", str(uuid.uuid4())),
            "node_name": node_cfg.get("name", "node"),
            "node_platform": node_cfg.get("platform", Platform.current()),
            "last_seen": utc_now(),
            "date_created": utc_now(),
        })
        config["instance_id"] = instance_id
        config.save()
        library = Library(lib_id, config, db, self.node)
        self._attach_services(library)
        with self._lock:
            self._libraries[lib_id] = library
        self._emit(LibraryManagerEvent.LOAD, library)
        return library

    def edit(self, lib_id: str, name: str | None = None,
             description: str | None = None) -> Library:
        library = self.get(lib_id)
        if name is not None:
            library.config["name"] = validate_library_name(name)
        if description is not None:
            library.config["description"] = description
        library.config.save()
        self._emit(LibraryManagerEvent.EDIT, library)
        return library

    def notify_instances_modified(self, library: Library) -> None:
        """Pairing added/changed instance rows — rebroadcast so NLM and
        watchers rebuild (LibraryManagerEvent::InstancesModified)."""
        self._emit(LibraryManagerEvent.INSTANCES_MODIFIED, library)

    def delete(self, lib_id: str) -> None:
        library = self.get(lib_id)
        self._emit(LibraryManagerEvent.DELETE, library)
        with self._lock:
            self._libraries.pop(lib_id, None)
        library.close()
        (self.dir / f"{lib_id}.sdlibrary").unlink(missing_ok=True)
        (self.dir / f"{lib_id}.db").unlink(missing_ok=True)

    # -- access -------------------------------------------------------------
    def get(self, lib_id: str) -> Library:
        with self._lock:
            if lib_id not in self._libraries:
                raise KeyError(f"library {lib_id} not loaded")
            return self._libraries[lib_id]

    def list(self) -> list[Library]:
        with self._lock:
            return list(self._libraries.values())

    def close(self) -> None:
        with self._lock:
            libs = list(self._libraries.values())
            self._libraries.clear()
        for lib in libs:
            lib.close()
