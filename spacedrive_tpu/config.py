"""Node configuration.

Equivalent of the reference's ``NodeConfig`` (core/src/node/config.rs:21-41):
a versioned JSON file ``node_state.sdconfig`` in the data dir holding the node
identity (uuid), display name, p2p keypair seed, platform, and the runtime
feature flags that survive restarts (api/mod.rs:131-167 restores them at boot).

TPU-native addition: the node records its accelerator topology (device kind,
chip count, mesh shape) so remote peers can route hashing work to nodes with
spare TPU capacity (the "shared TPU hasher service" of BASELINE.json config 5).
"""

from __future__ import annotations

import os
import platform as _platform
import secrets
import threading
import uuid
from pathlib import Path
from typing import Any

from .utils.migrator import VersionedConfig


class Platform:
    """Reference core/src/node/platform.rs enum."""

    UNKNOWN = 0
    WINDOWS = 1
    MACOS = 2
    LINUX = 3
    IOS = 4
    ANDROID = 5

    @staticmethod
    def current() -> int:
        return {"Linux": Platform.LINUX, "Darwin": Platform.MACOS, "Windows": Platform.WINDOWS}.get(
            _platform.system(), Platform.UNKNOWN
        )


class BackendFeature:
    """Runtime-toggleable feature flags (reference api/mod.rs:28-48)."""

    SYNC_EMIT_MESSAGES = "syncEmitMessages"
    FILES_OVER_P2P = "filesOverP2P"
    #: route image-thumbnail resizing through the batched device kernel
    #: (ops/resize_jax.py) instead of scalar PIL — this framework's flag
    TPU_THUMBNAILS = "tpuThumbnails"
    ALL = (SYNC_EMIT_MESSAGES, FILES_OVER_P2P, TPU_THUMBNAILS)


class NodeConfig(VersionedConfig):
    VERSION = 1
    FILENAME = "node_state.sdconfig"

    @classmethod
    def defaults(cls) -> dict[str, Any]:
        return {
            "id": str(uuid.uuid4()),
            "name": os.uname().nodename if hasattr(os, "uname") else "spacedrive-tpu",
            # ed25519 seed, hex; public identity derived in p2p layer
            "keypair_seed": secrets.token_hex(32),
            "platform": Platform.current(),
            "p2p_enabled": True,
            "p2p_port": None,              # TCP listen port (None = ephemeral)
            "p2p_discovery_port": None,    # UDP beacon port (None = no discovery)
            "p2p_static_peers": [],        # ["host:port", ...] for filtered LANs
            "p2p_auto_accept_library": None,  # headless auto-pair target
            "features": [],
            # TPU-native: accelerator inventory advertised to peers
            "accelerator": {"kind": None, "devices": 0, "mesh": []},
            "preferences": {},
        }

    @classmethod
    def load(cls, data_dir: str | Path) -> "NodeConfig":
        return cls.load_and_migrate(Path(data_dir) / cls.FILENAME)  # type: ignore[return-value]


class ConfigManager:
    """Thread-safe wrapper with write-through persistence, the analogue of the
    reference's ``config::Manager`` watch channel."""

    def __init__(self, config: NodeConfig) -> None:
        self._config = config
        self._lock = threading.Lock()

    def get(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._config.data)

    def write(self, **updates: Any) -> dict[str, Any]:
        with self._lock:
            self._config.data.update(updates)
            self._config.save()
            return dict(self._config.data)

    def toggle_feature(self, feature: str) -> bool:
        """Returns the new enabled state (reference toggleFeatureFlag)."""
        if feature not in BackendFeature.ALL:
            raise ValueError(f"unknown feature flag: {feature}")
        with self._lock:
            features = set(self._config.data.get("features", []))
            enabled = feature not in features
            (features.add if enabled else features.discard)(feature)
            self._config.data["features"] = sorted(features)
            self._config.save()
            return enabled

    def has_feature(self, feature: str) -> bool:
        with self._lock:
            return feature in self._config.data.get("features", [])
