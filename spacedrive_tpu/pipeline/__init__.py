"""Streaming scan pipeline: overlap DB paging, file I/O, device dispatch,
and commit across job steps.

PR 2 proved the kernel; BENCH_r05 proved the kernel alone does not move
``scan_e2e_files_per_sec`` — every step of a batched job ran strictly
sequentially (SELECT → gather → hash → transaction), so the double-buffering
inside ``TpuHasher._hash_sampled`` died at each step boundary. "GPUs as
Storage System Accelerators" (arxiv 1202.3669) and SEDD (arxiv 2501.01046)
both find that accelerator storage pipelines only win when I/O, transfer and
compute overlap *end-to-end*; this package is that layer.

A batched job opts in by returning a :class:`PipelineSpec` from
``StatefulJob.pipeline_spec()``. The spec names three stage callables that
the :class:`PipelineExecutor` runs on dedicated threads connected by bounded
queues (depth ``SD_PIPELINE_DEPTH``, default 2):

- **prefetcher** — ``pipeline_page``: pages the next step's rows and gathers
  sample messages (file I/O) while the current batch is hashing. Reads only;
  the ``pipeline-ordering`` sdlint pass rejects DB writes here. With
  ``SD_SCAN_SHARDS`` > 1 and a spec that provides ``split``/``shard``/
  ``merge`` callables, this stage fans each cursor page across parallel
  gather shard workers and an ordered ticket merger (the
  ``IngestLanes.submit`` shape) re-serializes them, so the dispatcher still
  sees exactly the sequential page stream.
- **dispatcher** — ``pipeline_process``: device/CPU compute. Bounded queues
  keep it fed so ≥2 hash batches are enqueued against jax's async dispatch
  (the sampled row pipeline's internal double-buffering supplies the
  in-flight depth per call).
- **committer** — ``pipeline_commit``: runs on the job's own thread, in
  strict batch order, and is the ONLY stage allowed to write the DB. Commit
  of batch N overlaps hashing of batch N+1 and paging of batch N+2.

Ordering invariants (see docs/architecture/scan-pipeline.md):

1. Commits are strictly ordered by batch sequence; the checkpoint cursor in
   ``data`` is only advanced by the committer, so a pause/crash resumes at
   the last *committed* batch — byte-identical to the sequential path.
2. CRDT ops are emitted inside commit, in the same per-row order as the
   sequential path, so the sync op-log is byte-identical too.
3. Pause/cancel/shutdown drain cleanly: speculative pages and in-flight
   hashes are discarded, never committed out of order.
"""

from .executor import (PipelineExecutor, pipeline_depth, pipeline_enabled,
                       scan_shards)
from .spec import PipelineSpec

__all__ = ["PipelineExecutor", "PipelineSpec", "pipeline_depth",
           "pipeline_enabled", "scan_shards"]
