"""Bounded-queue streaming executor for batched jobs.

Thread layout (one executor per pipelined job run):

    prefetcher ──pages──▶ dispatcher ──results──▶ committer (job thread)

Both queues are bounded (``SD_PIPELINE_DEPTH``), so a slow committer
backpressures the dispatcher and a slow dispatcher backpressures the
prefetcher — memory stays O((depth + group) × batch) no matter how far the
stages drift apart. The committer is the job's own worker thread: it polls
the command channel between commits exactly like the sequential step loop,
so Pause/Cancel/Shutdown land at a committed-GROUP boundary and the
serialized checkpoint only ever reflects committed work.

Group commit (``SD_COMMIT_GROUP``): up to N processed pages share one
durable transaction — each page's ``spec.commit`` runs in order and its own
``db.transaction()`` joins the outer scope, so BEGIN/COMMIT (and fsync/WAL
cost, and the ``commit`` fault seam) amortize over the group while row
contents and CRDT op order stay byte-identical to the per-page committer.
"""

from __future__ import annotations

import logging
import os
import queue
import sqlite3
import threading
import time
from typing import TYPE_CHECKING, Any

from .. import telemetry
from ..recovery import is_disk_full, note_disk_full
from ..utils.locks import SdLock
from ..utils.retry import RetryPolicy, is_device_wedge, is_transient, retry_call

if TYPE_CHECKING:
    from ..jobs.job import DynJob
    from ..jobs.worker import WorkerContext
    from .spec import PipelineSpec

logger = logging.getLogger(__name__)

#: per-stage accounting on the unified registry: busy = executing the
#: stage callable, blocked = waiting on a full downstream queue
#: (backpressure), idle = waiting on an empty upstream queue. busy time is
#: read straight off the stage spans, so /metrics, the jobTrace tree and
#: the report's pipeline_*_s metadata can never disagree.
_BUSY = telemetry.counter(
    "sd_pipeline_stage_busy_seconds",
    "time each pipeline stage spent executing its callable",
    labels=("stage",))
_BLOCKED = telemetry.counter(
    "sd_pipeline_stage_blocked_seconds",
    "time each stage spent blocked on a full downstream queue "
    "(backpressure)", labels=("stage",))
_IDLE = telemetry.counter(
    "sd_pipeline_stage_idle_seconds",
    "time each stage spent waiting on an empty upstream queue",
    labels=("stage",))
_COMMIT_TXNS = telemetry.counter(
    "sd_commit_txns_total",
    "durable transactions opened by the pipeline committer (group commit "
    "coalesces SD_COMMIT_GROUP pages into each)")
_COMMIT_PAGES = telemetry.counter(
    "sd_commit_txn_pages_total",
    "pipeline pages made durable through group-commit transactions")
_GATHER_SHARDS = telemetry.gauge(
    "sd_gather_shards",
    "parallel gather shards per page in the sharded prefetch stage "
    "(SD_SCAN_SHARDS; 1 = classic single-thread prefetch)")
_GATHER_INFLIGHT = telemetry.gauge(
    "sd_gather_inflight",
    "gather shard slices currently executing across the shard workers")
_SHARD_TASKS = telemetry.counter(
    "sd_gather_shard_tasks_total",
    "page slices executed per gather shard worker (occupancy skew across "
    "shards shows up as per-label imbalance)", labels=("shard",))

#: poll quantum for queue waits — also bounds pause latency, like the
#: sequential loop's between-steps command check cadence
_POLL_S = 0.05

#: how long a partial commit group may wait for more pages before it
#: flushes anyway. In a commit-bound pipeline the results queue never runs
#: dry and groups fill to SD_COMMIT_GROUP; in a page/hash-bound pipeline
#: this caps durability latency (pause itself is NOT delayed — a pause
#: discards the uncommitted group and serializes the last flushed state)
GROUP_LINGER_S = 0.5

#: the committer's own retry over ``spec.commit``: patient (it sits ABOVE
#: the _Txn-level busy retry, catching what escalates past that budget) and
#: cancel-aware — the backoff polls the command channel, so Pause/Cancel
#: unwinds within one poll interval. The retried batch never half-applies
#: because of the PipelineSpec commit contract (spec.py): durable effects
#: are transactional-or-idempotent and post-durable tail work is
#: best-effort/non-raising, so an exception out of ``spec.commit`` means
#: nothing durable happened for this batch.
COMMIT_RETRY = RetryPolicy(attempts=4, base_s=0.25, max_s=2.0,
                           multiplier=2.0, jitter=0.5, budget_s=15.0)

_DONE = object()


def drain_timeout() -> float:
    """Per-join bound when draining stage threads (``SD_PIPELINE_DRAIN_S``);
    a stage stuck in a hung device/IO call must not strand a pausing job."""
    try:
        return max(0.1, float(os.environ.get("SD_PIPELINE_DRAIN_S", "10")))
    except ValueError:
        return 10.0


class _StageFailure:
    """An exception captured on a stage thread, re-raised by the committer
    (sequential parity: a raised step exception is fatal to the job)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class _PageTicket:
    """Ordered-merge ticket for one split page — the ``IngestLanes.submit``
    shape: the coordinator enqueues the ticket to the merger BEFORE its
    shard slices fan out, so pages re-serialize in exactly split order no
    matter how the shard workers interleave. Passive holder: the shard
    workers fill ``results`` and count down ``remaining`` under ``lock``
    (the last finisher sets ``done``); the merger barriers on ``done``."""

    __slots__ = ("header", "parts", "results", "remaining", "done", "span",
                 "lock")

    def __init__(self, header: dict, parts: list, span: Any) -> None:
        self.header = header
        self.parts = parts
        self.results: list[Any] = [None] * len(parts)
        self.remaining = len(parts)
        self.done = threading.Event()
        #: the page's detached ``pipeline.page`` span — entered by the
        #: coordinator, parent of every shard span, exited by the merger
        self.span = span
        self.lock = threading.Lock()


def pipeline_enabled() -> bool:
    """Streaming execution is the default for jobs that opt in;
    ``SD_PIPELINE=0`` forces every job back onto the sequential step loop
    (the equivalence baseline)."""
    return os.environ.get("SD_PIPELINE", "1").lower() not in ("0", "false", "off")


def pipeline_depth() -> int:
    """Bounded-queue depth between stages (``SD_PIPELINE_DEPTH``, min 1)."""
    try:
        return max(1, int(os.environ.get("SD_PIPELINE_DEPTH", "2")))
    except ValueError:
        return 2


def scan_shards() -> int:
    """Parallel gather shards per page (``SD_SCAN_SHARDS``, clamped 1..16;
    default min(4, cores)). 1 disables sharding — the classic single
    prefetch thread, which is also the byte-identity baseline the shard
    matrix compares against."""
    raw = os.environ.get("SD_SCAN_SHARDS", "").strip()
    if raw:
        try:
            return max(1, min(int(raw), 16))
        except ValueError:
            pass
    return min(4, os.cpu_count() or 1)


def commit_group() -> int:
    """Pages coalesced per durable transaction (``SD_COMMIT_GROUP``, min 1,
    default 8). 1 restores the PR 3 one-txn-per-page committer — the
    equivalence baseline for the group-commit byte-identity matrix."""
    try:
        return max(1, int(os.environ.get("SD_COMMIT_GROUP", "8")))
    except ValueError:
        return 8


class PipelineExecutor:
    """Drive one pipelined job run; mutates the job's ``JobState`` exactly
    like the sequential loop in ``DynJob.run`` would."""

    def __init__(self, spec: "PipelineSpec", ctx: "WorkerContext",
                 dyn_job: "DynJob", errors: list[str]) -> None:
        self.spec = spec
        self.ctx = ctx
        self.dyn_job = dyn_job
        self.state = dyn_job.state
        self.errors = errors
        depth = spec.depth or pipeline_depth()
        self._pages: queue.Queue[Any] = queue.Queue(maxsize=depth)
        self._results: queue.Queue[Any] = queue.Queue(maxsize=depth)
        # sharded prefetch (ISSUE 17): when the spec provides the
        # split/shard/merge callables and SD_SCAN_SHARDS > 1, the page
        # stage fans each cursor page across shard workers and an ordered
        # merger re-serializes them. Both queues are bounded: tickets by
        # pipeline depth (pages in flight), slices by shards per ticket.
        self._shards = (scan_shards()
                        if (spec.split is not None and spec.shard is not None
                            and spec.merge is not None) else 1)
        self._sharded = self._shards > 1
        self._tickets: queue.Queue[Any] = queue.Queue(maxsize=depth)
        self._shard_q: queue.Queue[Any] = queue.Queue(
            maxsize=self._shards * (depth + 1))
        self._stop = threading.Event()
        #: the job's trace (set by the worker; None with telemetry off) —
        #: stage spans pin the run() wall span as their parent
        self.trace = getattr(dyn_job, "trace", None)
        self._wall_sp = None
        # per-stage wall time, read off the stage spans. Three different
        # threads accumulate here (prefetcher, dispatcher, committer);
        # the per-batch lock hold replaces the old "each attribute is
        # written by exactly one thread" convention with an invariant the
        # lockset pass and the runtime sanitizer can actually check
        self._stats_lock = SdLock("pipeline.executor.stats")
        self._page_s = 0.0
        self._hash_s = 0.0
        self._commit_s = 0.0
        self._batches = 0
        self._txns = 0

    def _persist_checkpoint(self) -> None:
        """Write the current (fully committed) state into the job report row
        so process death resumes here (jobs/manager.cold_resume revives
        RUNNING rows from report.data). One small autocommit UPDATE per
        group transaction; failures cost re-run work, never correctness."""
        db = getattr(getattr(self.ctx, "library", None), "db", None)
        if db is None:
            return
        try:
            report = self.dyn_job.report
            report.data = self.dyn_job.serialize_state()
            report.upsert(db)
        except Exception:
            logger.exception(
                "pipeline %s: checkpoint persist failed (resume falls back "
                "to the previous checkpoint)", self.dyn_job.job.NAME)

    # -- bounded put/get that never deadlock a drain -------------------------
    def _put(self, q: queue.Queue, item: Any) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _put_nowait_or_drop(self, q: queue.Queue, item: Any) -> None:
        """Best-effort forward of a failure marker: make room if needed (the
        committer only cares that it eventually sees the failure)."""
        while True:
            try:
                q.put_nowait(item)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass

    def _observe_shares(self, scratch: dict[str, Any]) -> None:
        """Publish measured stage shares (fraction of the pipeline wall
        each stage has consumed so far) into ``scratch`` — the feedback
        signal adaptive page sizing (``spec.adaptive``) reads before
        sizing the next page. Measurement only: the sizing law lives with
        the job, which knows its own pin/override rules."""
        wall_sp = self._wall_sp
        if wall_sp is None:
            return
        wall = wall_sp.elapsed_s()
        if wall <= 0.05:
            return
        with self._stats_lock:
            shares = {"page": self._page_s / wall,
                      "hash": self._hash_s / wall,
                      "commit": self._commit_s / wall}
        scratch["stage_shares"] = shares

    # -- stage threads -------------------------------------------------------
    def _prefetch_loop(self, budget: int) -> None:
        scratch: dict[str, Any] = {
            "step_index": self.state.step_number,
            "steps": self.state.steps,
            "shards": 1,
        }
        try:
            while (budget > 0 or self.spec.adaptive) \
                    and not self._stop.is_set():
                self._observe_shares(scratch)
                with telemetry.span(self.trace, "pipeline.page",
                                    parent=self._wall_sp) as sp:
                    payload = self.spec.page(self.ctx, self.state.data,
                                             scratch)
                with self._stats_lock:
                    self._page_s += sp.duration_s
                _BUSY.inc(sp.duration_s, stage="page")
                if payload is None:
                    break
                budget -= 1
                t0 = time.perf_counter()
                ok = self._put(self._pages, payload)
                _BLOCKED.inc(time.perf_counter() - t0, stage="page")
                if not ok:
                    return  # draining
            self._put(self._pages, _DONE)
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._pages, _StageFailure(e))

    # -- sharded prefetch: split coordinator / shard workers / merger --------
    def _split_loop(self, budget: int) -> None:
        scratch: dict[str, Any] = {
            "step_index": self.state.step_number,
            "steps": self.state.steps,
            "shards": self._shards,
        }
        try:
            while (budget > 0 or self.spec.adaptive) \
                    and not self._stop.is_set():
                self._observe_shares(scratch)
                # the page span is DETACHED: entered here, exited by the
                # merger once the page reassembles — its duration is the
                # page's true wall (split + shard fan-out + merge), and
                # every shard span pins it as parent so the trace tree
                # keeps one pipeline.page node per page
                sp = telemetry.span(self.trace, "pipeline.page",
                                    parent=self._wall_sp, detached=True,
                                    shards=self._shards)
                sp.__enter__()
                try:
                    with telemetry.span(self.trace, "pipeline.split",
                                        parent=sp):
                        header = self.spec.split(self.ctx, self.state.data,
                                                 scratch)
                except BaseException:
                    sp.__exit__(None, None, None)
                    raise
                if header is None:
                    # out-of-work probe: close and count it, exactly like
                    # the None-returning page call on the classic path
                    sp.__exit__(None, None, None)
                    with self._stats_lock:
                        self._page_s += sp.duration_s
                    _BUSY.inc(sp.duration_s, stage="page")
                    break
                budget -= 1
                parts = header.pop("parts")
                ticket = _PageTicket(header, parts, sp)
                # ticket BEFORE fan-out (the IngestLanes.submit order):
                # merge order is fixed here, shard completion order is free
                t0 = time.perf_counter()
                ok = self._put(self._tickets, ticket)
                if ok:
                    for idx in range(len(parts)):
                        if not self._put(self._shard_q, (ticket, idx)):
                            return  # draining
                _BLOCKED.inc(time.perf_counter() - t0, stage="page")
                if not ok:
                    return  # draining
            self._put(self._tickets, _DONE)
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._tickets, _StageFailure(e))

    def _shard_loop(self, shard_idx: int) -> None:
        """One gather worker: drains page slices off the shared shard
        queue in arrival order (work-stealing across pages — a slow slice
        of page N never idles workers that could start page N+1)."""
        label = str(shard_idx)
        while not self._stop.is_set():
            try:
                t0 = time.perf_counter()
                ticket, idx = self._shard_q.get(timeout=_POLL_S)
            except queue.Empty:
                _IDLE.inc(time.perf_counter() - t0, stage="gather")
                continue
            _GATHER_INFLIGHT.inc()
            result = None
            try:
                with telemetry.span(self.trace, "pipeline.gather",
                                    parent=ticket.span, shard=shard_idx,
                                    part=idx) as sp:
                    try:
                        result = self.spec.shard(self.ctx, self.state.data,
                                                 ticket.parts[idx])
                    except BaseException as e:  # noqa: BLE001 — merged, fatal
                        result = _StageFailure(e)
                        sp.set(failed=repr(e))
                _BUSY.inc(sp.duration_s, stage="gather")
                _SHARD_TASKS.inc(shard=label)
            except BaseException as e:  # noqa: BLE001 — span/metric plumbing
                # a slice result that already exists survives a telemetry
                # failure; a missing one becomes a failed slice
                if result is None:
                    result = _StageFailure(e)
            finally:
                # ticket accounting is unconditional: a slice that dies for
                # ANY reason must fail its page at the merger, never leave
                # `remaining` stuck and hang the pipeline
                _GATHER_INFLIGHT.dec()
                with ticket.lock:
                    ticket.results[idx] = result
                    ticket.remaining -= 1
                    last = ticket.remaining == 0
                if last:
                    ticket.done.set()

    def _merge_loop(self) -> None:
        """The ordered merger: completes tickets strictly in split order,
        reassembles each page via ``spec.merge`` and forwards it — so the
        dispatcher (and therefore hash and commit) sees exactly the
        sequential page stream regardless of shard interleaving."""
        try:
            while not self._stop.is_set():
                try:
                    t0 = time.perf_counter()
                    item = self._tickets.get(timeout=_POLL_S)
                except queue.Empty:
                    _IDLE.inc(time.perf_counter() - t0, stage="merge")
                    continue
                if item is _DONE or isinstance(item, _StageFailure):
                    self._put(self._pages, item)
                    return
                ticket = item
                t0 = time.perf_counter()
                while not ticket.done.wait(timeout=_POLL_S):
                    if self._stop.is_set():
                        return  # draining; the page span is abandoned
                _IDLE.inc(time.perf_counter() - t0, stage="merge")
                failure = next((r for r in ticket.results
                                if isinstance(r, _StageFailure)), None)
                if failure is not None:
                    # first failed slice fails the page — sequential
                    # parity with a raised pipeline_page; transient
                    # classification happens in the committer
                    ticket.span.__exit__(type(failure.exc), failure.exc,
                                         None)
                    self._put_nowait_or_drop(self._pages, failure)
                    return
                with telemetry.span(self.trace, "pipeline.merge",
                                    parent=ticket.span):
                    payload = self.spec.merge(self.ctx, self.state.data,
                                              ticket.header, ticket.results)
                ticket.span.__exit__(None, None, None)
                with self._stats_lock:
                    self._page_s += ticket.span.duration_s
                _BUSY.inc(ticket.span.duration_s, stage="page")
                t0 = time.perf_counter()
                ok = self._put(self._pages, payload)
                _BLOCKED.inc(time.perf_counter() - t0, stage="page")
                if not ok:
                    return  # draining
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._pages, _StageFailure(e))

    def _dispatch_loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    t0 = time.perf_counter()
                    item = self._pages.get(timeout=_POLL_S)
                except queue.Empty:
                    _IDLE.inc(time.perf_counter() - t0, stage="hash")
                    continue
                if item is _DONE or isinstance(item, _StageFailure):
                    self._put(self._results, item)
                    return
                with telemetry.span(self.trace, "pipeline.hash",
                                    parent=self._wall_sp) as sp:
                    result = self.spec.process(self.ctx, self.state.data,
                                               item)
                with self._stats_lock:
                    self._hash_s += sp.duration_s
                _BUSY.inc(sp.duration_s, stage="hash")
                t0 = time.perf_counter()
                ok = self._put(self._results, result)
                _BLOCKED.inc(time.perf_counter() - t0, stage="hash")
                if not ok:
                    return  # draining
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._results, _StageFailure(e))

    # -- the committer (job thread) ------------------------------------------
    def run(self) -> None:
        from ..jobs.error import JobError, JobPaused
        from ..jobs.job import merge_metadata

        state = self.state
        budget = len(state.steps) - state.step_number
        # an adaptive spec may legitimately need more (or fewer) pages
        # than init's fixed-size estimate — completion is page()→None,
        # the budget is only the non-adaptive parity bound
        if budget <= 0 and not self.spec.adaptive:
            return
        # the wall-clock span the stage spans nest under; its duration IS
        # pipeline_wall_s (metadata reads span data, not a parallel clock).
        # Entered BEFORE the stage threads start so its span_id exists for
        # their explicit-parent pins.
        wall_sp = telemetry.span(self.trace, "pipeline.run",
                                 job=self.dyn_job.job.NAME)
        wall_sp.__enter__()
        self._wall_sp = wall_sp
        _GATHER_SHARDS.set(self._shards)
        if self._sharded:
            threads = [
                threading.Thread(target=self._split_loop, args=(budget,),
                                 daemon=True, name="pipeline-prefetch"),
                *[threading.Thread(target=self._shard_loop, args=(i,),
                                   daemon=True, name=f"pipeline-gather-{i}")
                  for i in range(self._shards)],
                threading.Thread(target=self._merge_loop,
                                 daemon=True, name="pipeline-merge"),
                threading.Thread(target=self._dispatch_loop,
                                 daemon=True, name="pipeline-dispatch"),
            ]
        else:
            threads = [
                threading.Thread(target=self._prefetch_loop, args=(budget,),
                                 daemon=True, name="pipeline-prefetch"),
                threading.Thread(target=self._dispatch_loop,
                                 daemon=True, name="pipeline-dispatch"),
            ]
        for t in threads:
            t.start()

        # -- group commit: coalesce up to SD_COMMIT_GROUP processed pages
        # into ONE durable transaction. Each page's spec.commit runs in
        # arrival order; its own db.transaction() joins the outer scope
        # (models/base._Txn re-entrancy), so durability — and the `commit`
        # fault seam — lands once per GROUP. The checkpoint cursor and
        # step_number still only advance with committed work: on any
        # failure the whole group rolls back AND the in-memory `data`
        # snapshot is restored before the exception escapes, so a pause
        # arriving during the retry backoff serializes the last durable
        # group boundary, never a torn group.
        group_n = self.spec.group or commit_group()
        db = getattr(getattr(self.ctx, "library", None), "db", None)
        pending: list[Any] = []
        pending_since = 0.0  # perf_counter of the oldest un-flushed page

        def _flush() -> None:
            if not pending:
                return
            # spec.commit mutates only top-level keys of `data` (the
            # checkpoint-cursor contract, spec.py) — a shallow snapshot
            # makes the group attempt restartable
            snapshot = dict(state.data)

            def attempt() -> list[Any]:
                try:
                    results: list[Any] = []
                    if len(pending) == 1 or db is None:
                        for it in pending:
                            results.append(
                                self.spec.commit(self.ctx, state.data, it))
                    else:
                        with db.transaction():
                            for it in pending:
                                results.append(
                                    self.spec.commit(self.ctx, state.data,
                                                     it))
                    return results
                except BaseException:
                    state.data.clear()
                    state.data.update(snapshot)
                    raise

            with telemetry.span(self.trace, "pipeline.commit",
                                pages=len(pending)) as sp:
                try:
                    results = retry_call(
                        attempt, policy=COMMIT_RETRY, classify=is_transient,
                        cancel_check=lambda: self.ctx.check_commands(
                            self.dyn_job),
                        label=f"{self.dyn_job.job.NAME}-commit")
                except (OSError, sqlite3.OperationalError) as e:
                    if not is_disk_full(e):
                        raise
                    # full disk mid-commit (OSError ENOSPC from artifact
                    # IO, or SQLite's own SQLITE_FULL "database or disk is
                    # full"): retrying cannot free space and failing would
                    # throw away the whole run — checkpoint-pause at the
                    # last durable group instead (the group rolled back and
                    # `data` was snapshot-restored above), resumable once
                    # the operator frees space
                    note_disk_full("commit")
                    self.errors.append(
                        f"commit hit a full disk (ENOSPC); checkpoint-"
                        f"paused at batch {self._batches}: {e!r}")
                    logger.error(
                        "pipeline %s: disk full during commit; pausing at "
                        "committed batch %d", self.dyn_job.job.NAME,
                        self._batches)
                    raise JobPaused(self.dyn_job.serialize_state(),
                                    errors=self.errors) from e
            with self._stats_lock:
                self._commit_s += sp.duration_s
                self._txns += 1
            _BUSY.inc(sp.duration_s, stage="commit")
            _COMMIT_TXNS.inc()
            _COMMIT_PAGES.inc(len(pending))
            pending.clear()
            for result in results:
                with self._stats_lock:
                    self._batches += 1
                if result.more_steps:
                    raise JobError(
                        f"{self.dyn_job.job.NAME}: pipelined jobs cannot "
                        f"append steps mid-run")
                if result.metadata:
                    merge_metadata(state.run_metadata, result.metadata)
                self.errors.extend(result.errors)
                state.step_number += 1
                if state.step_number > len(state.steps):
                    # adaptive paging produced more pages than init's
                    # fixed-size estimate: mirror the estimate (content
                    # cloned from the last step) so progress totals and
                    # resume budgets stay coherent
                    state.steps.append(dict(state.steps[-1]))
                self.ctx.progress(completed_task_count=state.step_number)
            # durable crash checkpoint (ISSUE 9): persist the serialized
            # state now that this group is committed, so a SIGKILL resumes
            # at this boundary instead of step 0. Best-effort and OUTSIDE
            # the group transaction: a kill between the commit and this
            # upsert resumes one group early, and re-running a committed
            # group is idempotent (its rows are no longer orphans).
            self._persist_checkpoint()
            # serve-pool invalidation (ISSUE 11): the group is durable —
            # bump the library's read watermark so a pool worker can
            # never serve a directory page cached before this commit.
            # Emitted AFTER COMMIT by construction (we are past the retry
            # block), per-txn not per-page, and a node-less library
            # (unit-test contexts) makes it a no-op.
            library = getattr(self.ctx, "library", None)
            if library is not None and hasattr(library, "emit"):
                library.emit("db.commit", {"source": "pipeline",
                                           "txns": self._txns})

        try:
            while True:
                # between-commits command poll: JobPaused serializes the
                # state as of the last committed group, nothing speculative
                self.ctx.check_commands(self.dyn_job)
                try:
                    t0 = time.perf_counter()
                    item = self._results.get(timeout=_POLL_S)
                except queue.Empty:
                    _IDLE.inc(time.perf_counter() - t0, stage="commit")
                    # upstream is slow: a partial group that lingered past
                    # its window commits now rather than holding completed
                    # pages hostage to queue cadence — page/hash-bound
                    # pipelines degrade toward smaller groups, never stall
                    if pending and (time.perf_counter() - pending_since
                                    > GROUP_LINGER_S):
                        _flush()
                    continue
                if item is _DONE:
                    _flush()
                    break
                if isinstance(item, _StageFailure):
                    # completed pages first: the drain lands on an ordered
                    # committed-group boundary before supervision acts
                    _flush()
                    # stage supervision: a prefetch/dispatch thread that
                    # crashed on a TRANSIENT class (flaky IO, device wedge,
                    # injected chaos) drains to an ordered checkpoint-pause
                    # — the serialized state reflects only committed
                    # batches, so resume re-runs the lost work exactly.
                    # Deterministic failures stay fatal (a poisoned-input
                    # pause would resume into the same crash forever).
                    exc = item.exc
                    if is_transient(exc) or is_device_wedge(exc):
                        self.errors.append(
                            f"pipeline stage failed transiently; checkpoint-"
                            f"paused at batch {self._batches}: {exc!r}")
                        logger.warning(
                            "pipeline %s: transient stage failure, pausing "
                            "at committed batch %d: %r",
                            self.dyn_job.job.NAME, self._batches, exc)
                        raise JobPaused(self.dyn_job.serialize_state(),
                                        errors=self.errors)
                    raise exc
                if not pending:
                    pending_since = time.perf_counter()
                pending.append(item)
                if len(pending) >= group_n:
                    _flush()
        finally:
            wall_sp.__exit__(None, None, None)
            self._stop.set()
            # unblock producers stuck on a full queue, then join
            for q in (self._pages, self._results, self._tickets,
                      self._shard_q):
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            drain_s = drain_timeout()
            for t in threads:
                t.join(timeout=drain_s)
                if not t.is_alive():
                    continue
                # a stage stuck in a hung device/IO call (the wedged-tunnel
                # failure mode): escalate to one bounded hard-join, then
                # give the thread up — it is a daemon, its result is
                # discarded, and the leak becomes a REPORT soft error (not
                # just a log line) so a stuck gather cannot silently strand
                # a paused job; a resumed run shares the device with it
                # until it dies, which the operator must be able to see
                logger.warning(
                    "pipeline %s: %s still running after %.1fs drain "
                    "timeout (stuck stage call?); hard-joining once more",
                    self.dyn_job.job.NAME, t.name, drain_s)
                t.join(timeout=drain_s)
                if t.is_alive():
                    msg = (f"pipeline stage thread {t.name} leaked: still "
                           f"running {2 * drain_s:.1f}s after drain "
                           f"(stuck in a hung gather/device call); its "
                           f"result is discarded")
                    logger.error("pipeline %s: %s", self.dyn_job.job.NAME,
                                 msg)
                    self.errors.append(msg)

        # pages ran dry before the estimated step count (rows shrank since
        # init, exactly like sequential steps whose SELECT comes back empty):
        # fast-forward to the sequential loop's terminal step_number
        if state.step_number < len(state.steps):
            state.step_number = len(state.steps)
            self.ctx.progress(completed_task_count=state.step_number)
        # the report's stage timings are READ FROM SPAN DATA: the _page_s/
        # _hash_s/_commit_s accumulators sum exactly the pipeline.* span
        # durations above (and still work with telemetry off, where spans
        # degrade to bare timers), so jobTrace and the scan report reconcile
        # by construction
        merge_metadata(state.run_metadata, {
            "pipeline_page_s": self._page_s,
            "pipeline_hash_s": self._hash_s,
            "pipeline_commit_s": self._commit_s,
            "pipeline_wall_s": wall_sp.duration_s,
            "pipeline_batches": self._batches,
            # a string on purpose: merge_metadata SUMS numerics across
            # pause/resume cycles, and shard counts must overwrite
            "pipeline_shards": str(self._shards),
            "commit_txns": self._txns,
        })
        logger.debug(
            "pipeline %s: %d batches in %d txns, page %.3fs | hash %.3fs | "
            "commit %.3fs | wall %.3fs", self.dyn_job.job.NAME, self._batches,
            self._txns, self._page_s, self._hash_s, self._commit_s,
            wall_sp.duration_s)
