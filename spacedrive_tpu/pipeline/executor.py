"""Bounded-queue streaming executor for batched jobs.

Thread layout (one executor per pipelined job run):

    prefetcher ──pages──▶ dispatcher ──results──▶ committer (job thread)

Both queues are bounded (``SD_PIPELINE_DEPTH``), so a slow committer
backpressures the dispatcher and a slow dispatcher backpressures the
prefetcher — memory stays O(depth × batch) no matter how far the stages
drift apart. The committer is the job's own worker thread: it polls the
command channel between commits exactly like the sequential step loop, so
Pause/Cancel/Shutdown land at a committed-batch boundary and the serialized
checkpoint only ever reflects committed work.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import TYPE_CHECKING, Any

from ..utils.retry import RetryPolicy, is_device_wedge, is_transient, retry_call

if TYPE_CHECKING:
    from ..jobs.job import DynJob
    from ..jobs.worker import WorkerContext
    from .spec import PipelineSpec

logger = logging.getLogger(__name__)

#: poll quantum for queue waits — also bounds pause latency, like the
#: sequential loop's between-steps command check cadence
_POLL_S = 0.05

#: the committer's own retry over ``spec.commit``: patient (it sits ABOVE
#: the _Txn-level busy retry, catching what escalates past that budget) and
#: cancel-aware — the backoff polls the command channel, so Pause/Cancel
#: unwinds within one poll interval. The retried batch never half-applies
#: because of the PipelineSpec commit contract (spec.py): durable effects
#: are transactional-or-idempotent and post-durable tail work is
#: best-effort/non-raising, so an exception out of ``spec.commit`` means
#: nothing durable happened for this batch.
COMMIT_RETRY = RetryPolicy(attempts=4, base_s=0.25, max_s=2.0,
                           multiplier=2.0, jitter=0.5, budget_s=15.0)

_DONE = object()


def drain_timeout() -> float:
    """Per-join bound when draining stage threads (``SD_PIPELINE_DRAIN_S``);
    a stage stuck in a hung device/IO call must not strand a pausing job."""
    try:
        return max(0.1, float(os.environ.get("SD_PIPELINE_DRAIN_S", "10")))
    except ValueError:
        return 10.0


class _StageFailure:
    """An exception captured on a stage thread, re-raised by the committer
    (sequential parity: a raised step exception is fatal to the job)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


def pipeline_enabled() -> bool:
    """Streaming execution is the default for jobs that opt in;
    ``SD_PIPELINE=0`` forces every job back onto the sequential step loop
    (the equivalence baseline)."""
    return os.environ.get("SD_PIPELINE", "1").lower() not in ("0", "false", "off")


def pipeline_depth() -> int:
    """Bounded-queue depth between stages (``SD_PIPELINE_DEPTH``, min 1)."""
    try:
        return max(1, int(os.environ.get("SD_PIPELINE_DEPTH", "2")))
    except ValueError:
        return 2


class PipelineExecutor:
    """Drive one pipelined job run; mutates the job's ``JobState`` exactly
    like the sequential loop in ``DynJob.run`` would."""

    def __init__(self, spec: "PipelineSpec", ctx: "WorkerContext",
                 dyn_job: "DynJob", errors: list[str]) -> None:
        self.spec = spec
        self.ctx = ctx
        self.dyn_job = dyn_job
        self.state = dyn_job.state
        self.errors = errors
        depth = spec.depth or pipeline_depth()
        self._pages: queue.Queue[Any] = queue.Queue(maxsize=depth)
        self._results: queue.Queue[Any] = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # per-stage wall time; each attribute is written by exactly one thread
        self._page_s = 0.0
        self._hash_s = 0.0
        self._commit_s = 0.0
        self._batches = 0

    # -- bounded put/get that never deadlock a drain -------------------------
    def _put(self, q: queue.Queue, item: Any) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _put_nowait_or_drop(self, q: queue.Queue, item: Any) -> None:
        """Best-effort forward of a failure marker: make room if needed (the
        committer only cares that it eventually sees the failure)."""
        while True:
            try:
                q.put_nowait(item)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass

    # -- stage threads -------------------------------------------------------
    def _prefetch_loop(self, budget: int) -> None:
        scratch: dict[str, Any] = {
            "step_index": self.state.step_number,
            "steps": self.state.steps,
        }
        try:
            while budget > 0 and not self._stop.is_set():
                t0 = time.perf_counter()
                payload = self.spec.page(self.ctx, self.state.data, scratch)
                self._page_s += time.perf_counter() - t0
                if payload is None:
                    break
                budget -= 1
                if not self._put(self._pages, payload):
                    return  # draining
            self._put(self._pages, _DONE)
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._pages, _StageFailure(e))

    def _dispatch_loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    item = self._pages.get(timeout=_POLL_S)
                except queue.Empty:
                    continue
                if item is _DONE or isinstance(item, _StageFailure):
                    self._put(self._results, item)
                    return
                t0 = time.perf_counter()
                result = self.spec.process(self.ctx, self.state.data, item)
                self._hash_s += time.perf_counter() - t0
                if not self._put(self._results, result):
                    return  # draining
        except BaseException as e:  # noqa: BLE001 — forwarded, fatal
            self._put_nowait_or_drop(self._results, _StageFailure(e))

    # -- the committer (job thread) ------------------------------------------
    def run(self) -> None:
        from ..jobs.error import JobError, JobPaused
        from ..jobs.job import merge_metadata

        state = self.state
        wall0 = time.perf_counter()
        budget = len(state.steps) - state.step_number
        if budget <= 0:
            return
        threads = [
            threading.Thread(target=self._prefetch_loop, args=(budget,),
                             daemon=True, name="pipeline-prefetch"),
            threading.Thread(target=self._dispatch_loop,
                             daemon=True, name="pipeline-dispatch"),
        ]
        for t in threads:
            t.start()
        try:
            while True:
                # between-commits command poll: JobPaused serializes the
                # state as of the last committed batch, nothing speculative
                self.ctx.check_commands(self.dyn_job)
                try:
                    item = self._results.get(timeout=_POLL_S)
                except queue.Empty:
                    continue
                if item is _DONE:
                    break
                if isinstance(item, _StageFailure):
                    # stage supervision: a prefetch/dispatch thread that
                    # crashed on a TRANSIENT class (flaky IO, device wedge,
                    # injected chaos) drains to an ordered checkpoint-pause
                    # — the serialized state reflects only committed
                    # batches, so resume re-runs the lost work exactly.
                    # Deterministic failures stay fatal (a poisoned-input
                    # pause would resume into the same crash forever).
                    exc = item.exc
                    if is_transient(exc) or is_device_wedge(exc):
                        self.errors.append(
                            f"pipeline stage failed transiently; checkpoint-"
                            f"paused at batch {self._batches}: {exc!r}")
                        logger.warning(
                            "pipeline %s: transient stage failure, pausing "
                            "at committed batch %d: %r",
                            self.dyn_job.job.NAME, self._batches, exc)
                        raise JobPaused(self.dyn_job.serialize_state(),
                                        errors=self.errors)
                    raise exc
                t0 = time.perf_counter()
                result = retry_call(
                    lambda: self.spec.commit(self.ctx, state.data, item),
                    policy=COMMIT_RETRY, classify=is_transient,
                    cancel_check=lambda: self.ctx.check_commands(self.dyn_job),
                    label=f"{self.dyn_job.job.NAME}-commit")
                self._commit_s += time.perf_counter() - t0
                self._batches += 1
                if result.more_steps:
                    raise JobError(
                        f"{self.dyn_job.job.NAME}: pipelined jobs cannot "
                        f"append steps mid-run")
                if result.metadata:
                    merge_metadata(state.run_metadata, result.metadata)
                self.errors.extend(result.errors)
                state.step_number += 1
                self.ctx.progress(completed_task_count=state.step_number)
        finally:
            self._stop.set()
            # unblock producers stuck on a full queue, then join
            for q in (self._pages, self._results):
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            drain_s = drain_timeout()
            for t in threads:
                t.join(timeout=drain_s)
                if not t.is_alive():
                    continue
                # a stage stuck in a hung device/IO call (the wedged-tunnel
                # failure mode): escalate to one bounded hard-join, then
                # give the thread up — it is a daemon, its result is
                # discarded, and the leak becomes a REPORT soft error (not
                # just a log line) so a stuck gather cannot silently strand
                # a paused job; a resumed run shares the device with it
                # until it dies, which the operator must be able to see
                logger.warning(
                    "pipeline %s: %s still running after %.1fs drain "
                    "timeout (stuck stage call?); hard-joining once more",
                    self.dyn_job.job.NAME, t.name, drain_s)
                t.join(timeout=drain_s)
                if t.is_alive():
                    msg = (f"pipeline stage thread {t.name} leaked: still "
                           f"running {2 * drain_s:.1f}s after drain "
                           f"(stuck in a hung gather/device call); its "
                           f"result is discarded")
                    logger.error("pipeline %s: %s", self.dyn_job.job.NAME,
                                 msg)
                    self.errors.append(msg)

        # pages ran dry before the estimated step count (rows shrank since
        # init, exactly like sequential steps whose SELECT comes back empty):
        # fast-forward to the sequential loop's terminal step_number
        if state.step_number < len(state.steps):
            state.step_number = len(state.steps)
            self.ctx.progress(completed_task_count=state.step_number)
        merge_metadata(state.run_metadata, {
            "pipeline_page_s": self._page_s,
            "pipeline_hash_s": self._hash_s,
            "pipeline_commit_s": self._commit_s,
            "pipeline_wall_s": time.perf_counter() - wall0,
            "pipeline_batches": self._batches,
        })
        logger.debug(
            "pipeline %s: %d batches, page %.3fs | hash %.3fs | commit %.3fs "
            "| wall %.3fs", self.dyn_job.job.NAME, self._batches, self._page_s,
            self._hash_s, self._commit_s, time.perf_counter() - wall0)
