"""The contract a batched job hands the streaming executor.

Stage callables follow the ``pipeline_page`` / ``pipeline_process`` /
``pipeline_commit`` naming convention — the ``pipeline-ordering`` sdlint
pass keys off those names to enforce that prefetch/dispatch stages never
write the DB (all commits go through the committer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class PipelineSpec:
    """Three stage callables + an optional queue-depth override.

    ``page(ctx, data, scratch) -> payload | None``
        Prefetch thread. Pages the next batch of rows (DB *reads* only) and
        gathers its sample messages (file I/O). ``scratch`` is a pipeline-
        local dict (NOT checkpointed) seeded with ``step_index``/``steps``;
        page keeps its speculative cursor there, never in ``data``. Returns
        ``None`` when the job is out of work.

    ``process(ctx, data, payload) -> payload``
        Dispatch thread. Device/CPU compute over the gathered batch. May
        mutate and return the payload.

    ``commit(ctx, data, payload) -> StepResult``
        Job thread, strict batch order, the only stage that may write the
        DB (and the only place the checkpoint cursor in ``data`` advances).
        RETRY CONTRACT: the committer re-invokes ``commit`` on transient
        failures (executor.COMMIT_RETRY), so durable effects must be
        transactional-or-idempotent and anything AFTER the durable point
        must be best-effort (caught and logged, never raised) — an
        exception escaping ``commit`` asserts that nothing durable
        happened for this batch.
        GROUP-COMMIT CONTRACT: the committer may run several ``commit``
        calls inside ONE outer transaction (executor.commit_group), rolling
        all of them back together on failure. Durable writes must therefore
        go through ``db.transaction()`` (joining the outer scope), and
        checkpoint mutations to ``data`` must be top-level key assignments —
        the committer restores a shallow snapshot of ``data`` when a group
        attempt fails, so nested-structure mutations would leak across a
        rollback. The ``commit-discipline`` sdlint pass enforces the write
        side.
    """

    page: Callable[..., Any]
    process: Callable[..., Any]
    commit: Callable[..., Any]
    depth: int | None = None
    #: pages per durable transaction; None → executor.commit_group()
    group: int | None = None
