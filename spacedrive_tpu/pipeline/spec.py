"""The contract a batched job hands the streaming executor.

Stage callables follow the ``pipeline_page`` / ``pipeline_process`` /
``pipeline_commit`` naming convention — the ``pipeline-ordering`` sdlint
pass keys off those names to enforce that prefetch/dispatch stages never
write the DB (all commits go through the committer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class PipelineSpec:
    """Three stage callables + an optional queue-depth override.

    ``page(ctx, data, scratch) -> payload | None``
        Prefetch thread. Pages the next batch of rows (DB *reads* only) and
        gathers its sample messages (file I/O). ``scratch`` is a pipeline-
        local dict (NOT checkpointed) seeded with ``step_index``/``steps``;
        page keeps its speculative cursor there, never in ``data``. Returns
        ``None`` when the job is out of work.

    ``process(ctx, data, payload) -> payload``
        Dispatch thread. Device/CPU compute over the gathered batch. May
        mutate and return the payload.

    ``commit(ctx, data, payload) -> StepResult``
        Job thread, strict batch order, the only stage that may write the
        DB (and the only place the checkpoint cursor in ``data`` advances).
        RETRY CONTRACT: the committer re-invokes ``commit`` on transient
        failures (executor.COMMIT_RETRY), so durable effects must be
        transactional-or-idempotent and anything AFTER the durable point
        must be best-effort (caught and logged, never raised) — an
        exception escaping ``commit`` asserts that nothing durable
        happened for this batch.
        GROUP-COMMIT CONTRACT: the committer may run several ``commit``
        calls inside ONE outer transaction (executor.commit_group), rolling
        all of them back together on failure. Durable writes must therefore
        go through ``db.transaction()`` (joining the outer scope), and
        checkpoint mutations to ``data`` must be top-level key assignments —
        the committer restores a shallow snapshot of ``data`` when a group
        attempt fails, so nested-structure mutations would leak across a
        rollback. The ``commit-discipline`` sdlint pass enforces the write
        side.

    Optional SHARDED PREFETCH (``SD_SCAN_SHARDS`` > 1 and all three set —
    otherwise the executor runs ``page`` exactly as before):

    ``split(ctx, data, scratch) -> header | None``
        Split-coordinator thread. Pages the next cursor window (cheap
        id-only DB read), advances the speculative cursor in ``scratch``,
        and returns a header dict whose ``"parts"`` key is a list of
        disjoint, **contiguous, ordered** work slices — one per gather
        shard. ``scratch["shards"]`` carries the active shard count.
        Returns ``None`` when out of work. Same read-only contract as
        ``page``.

    ``shard(ctx, data, part) -> part_result``
        Gather-worker threads, several concurrently. Runs one slice's row
        SELECT + sample gather. MUST be pure per-slice (no DB writes, no
        shared mutable state): slices of one page may run in any order
        and interleave with slices of later pages.

    ``merge(ctx, data, header, results) -> payload``
        Ordered-merger thread. Reassembles the shard results (in slice
        order) into exactly the payload ``page`` would have produced for
        the same cursor window — the byte-identity contract: hash and
        commit must not be able to tell a merged page from a sequential
        one.
    """

    page: Callable[..., Any]
    process: Callable[..., Any]
    commit: Callable[..., Any]
    depth: int | None = None
    #: pages per durable transaction; None → executor.commit_group()
    group: int | None = None
    #: sharded-prefetch callables (all three or none)
    split: Callable[..., Any] | None = None
    shard: Callable[..., Any] | None = None
    merge: Callable[..., Any] | None = None
    #: True when the job sizes its own pages from the executor's measured
    #: ``stage_shares`` feedback (scratch) — tells the executor that page
    #: count may diverge from init's fixed-size step estimate, so the
    #: page budget becomes advisory and completion is ``page()`` → None
    adaptive: bool = False
