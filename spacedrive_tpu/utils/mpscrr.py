"""mpscrr: multi-producer / single-consumer request-RESPONSE channel.

Reference: core/src/util/mpscrr.rs (330 LoC) — the library manager's event
subscription uses it so an emitter can await acknowledgement from every
subscriber before proceeding (load ordering depends on it: watchers, NLM,
and job cold-resume must have processed Load before boot continues).

Shape: ``channel()`` returns (Sender, Receiver). Each ``send`` enqueues a
Request carrying the message and a response slot; the consumer handles the
request and ``respond``s (any value; None = plain ack), unblocking the
producer. Dropping/closing the receiver wakes all pending producers with
ChannelClosed, mirroring the Rust half's drop semantics.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator


class ChannelClosed(Exception):
    pass


class Request:
    """One in-flight message; the consumer must call respond() exactly once."""

    __slots__ = ("message", "_event", "_response", "_closed")

    def __init__(self, message: Any) -> None:
        self.message = message
        self._event = threading.Event()
        self._response: Any = None
        self._closed = False

    def respond(self, value: Any = None) -> None:
        self._response = value
        self._event.set()

    def _abort(self) -> None:
        self._closed = True
        self._event.set()

    def wait(self, timeout: float | None = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("no response from receiver")
        if self._closed:
            raise ChannelClosed("receiver dropped before responding")
        return self._response


class Receiver:
    def __init__(self, capacity: int = 256) -> None:
        self._q: queue.Queue[Request] = queue.Queue(maxsize=capacity)
        self._closed = threading.Event()

    def recv(self, timeout: float | None = None) -> Request | None:
        if self._closed.is_set() and self._q.empty():
            return None
        try:
            req = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return req

    def __iter__(self) -> Iterator[Request]:
        while True:
            if self._closed.is_set() and self._q.empty():
                return
            try:
                req = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if req is None:  # close sentinel
                return
            yield req

    def close(self) -> None:
        """Wake pending producers with ChannelClosed; stop iteration."""
        self._closed.set()
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req._abort()
        try:
            self._q.put_nowait(None)  # unblock a blocked iterator
        except queue.Full:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class Sender:
    """Holds the Receiver WEAKLY (like the Rust half: a sender must not keep
    a dropped receiver alive) — a garbage-collected, never-closed receiver
    reads as ChannelClosed on the next send instead of leaking forever."""

    def __init__(self, receiver: Receiver) -> None:
        import weakref

        self._receiver = weakref.ref(receiver)

    def send(self, message: Any, timeout: float | None = None) -> Any:
        """Enqueue + block for the consumer's response (ack)."""
        return self.send_async(message).wait(timeout)

    def send_async(self, message: Any) -> Request:
        """Enqueue without waiting; call .wait() on the returned Request."""
        receiver = self._receiver()
        if receiver is None or receiver.closed:
            raise ChannelClosed("receiver is closed or collected")
        req = Request(message)
        try:
            receiver._q.put(req, timeout=5)
        except queue.Full:
            # a full queue means SLOW, not gone — closed is the only
            # gone-signal (a caller must not evict a live-but-busy consumer)
            raise TimeoutError("receiver queue full (consumer is slow)")
        if receiver.closed:
            req._abort()
        return req


def channel(capacity: int = 256) -> tuple[Sender, Receiver]:
    rx = Receiver(capacity)
    return Sender(rx), rx
