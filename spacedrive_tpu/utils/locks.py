"""Named locks with an opt-in runtime concurrency sanitizer (ISSUE 14).

Every one of this system's worst production bugs has been a concurrency
bug found late: the PR 8 self-deadlock (``IngestBudget._shed``
re-acquiring the non-reentrant lock ``try_admit`` already held) shipped
and was only caught in review; the PR 12 merger races took a 64-peer
soak to flush out. The static half of the discipline is the sdlint
``lockset`` pass (analysis/passes/lockset.py); this module is the
dynamic half: the hot shared-state modules name their locks
(``SdLock("db.writer")``), and an opt-in sanitizer turns every chaos
soak into a deadlock detector.

Zero-cost disabled fast path
----------------------------
``SdLock(name)`` / ``SdRLock(name)`` are FACTORIES: with
``SD_LOCK_SANITIZER`` unset they return the bare
``threading.Lock()``/``RLock()`` — not a wrapper, the real object — so
the production acquire/release path pays literally nothing for the
naming (the ``lock_overhead`` A/B in bench.py scan mode keeps this
honest). The enablement is read at lock CREATION time: processes opt in
by setting the env var before start (the chaos harnesses inherit it
into their node subprocesses).

The sanitizer (``SD_LOCK_SANITIZER=1``)
---------------------------------------
Enabled, the factories return instrumented locks feeding three
process-wide structures:

- **per-thread held-lock stacks**: every sanitized acquire pushes
  (lock, name, acquisition stack); release pops. A same-thread
  re-acquisition of a non-reentrant lock raises
  :class:`LockReacquireError` carrying BOTH acquisition stacks —
  an immediate diagnostic instead of the silent hang the PR 8 bug
  produced (``threading.Lock`` blocks forever, no error, no log).
- **a global lock-order graph**: acquiring B while holding A records
  the edge A→B (keyed by lock NAME — the role, not the instance — with
  the first-witness stacks on both sides). An edge that closes a cycle
  raises :class:`LockOrderError` BEFORE blocking, so the classic
  two-thread ABBA reports (with both threads' stacks) instead of
  deadlocking. Same-name edges are skipped: two instances of the same
  role taken in sequence (per-library DB handles) are a hierarchy, not
  an inversion — the same-instance case is covered by the re-acquisition
  check above.
- **contention telemetry**: ``sd_lock_wait_seconds{name}`` (contended
  acquisitions only — the uncontended path pays one non-blocking try),
  ``sd_lock_hold_seconds{name}`` and ``sd_lock_contended_total{name}``.

Every violation also lands in a process-wide ledger
(:func:`violations`) so a soak can assert "no cycles, no re-acquisitions"
after the fact even where the raise was swallowed by a worker's
error handling.

Re-entrancy guard: the sanitizer's own bookkeeping records telemetry,
and the telemetry registry's family locks are themselves sanitized —
a thread-local ``busy`` flag makes nested sanitized acquires inside the
bookkeeping degrade to raw acquires, terminating the recursion.

Idiom boundary: the sanitizer models the ``with lock:`` /
acquire-release-on-one-thread discipline every migrated module uses.
A ``threading.Lock`` released by a DIFFERENT thread than its acquirer
(the Lock-as-semaphore signal pattern) is legal for the raw primitive
but outside this model: the acquirer's held-stack entry would go stale
and its next acquire would misreport a re-acquisition. No migrated
lock does this — use ``threading.Event``/``Semaphore`` for cross-thread
signaling, which is what the codebase already does.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any

SANITIZER_ENV = "SD_LOCK_SANITIZER"

#: frames kept per acquisition stack in reports (innermost last)
_STACK_DEPTH = 16


def sanitizer_enabled() -> bool:
    return os.environ.get(SANITIZER_ENV, "").strip().lower() in (
        "1", "on", "true", "yes")


class LockSanitizerError(RuntimeError):
    """Base for sanitizer diagnostics; carries the structured report."""

    def __init__(self, message: str, report: dict[str, Any]) -> None:
        super().__init__(message)
        self.report = report


class LockReacquireError(LockSanitizerError):
    """Same thread re-acquired a non-reentrant lock it already holds —
    with a bare ``threading.Lock`` this is a guaranteed self-deadlock."""


class LockOrderError(LockSanitizerError):
    """This acquisition would close a cycle in the global lock-order
    graph (the ABBA shape): some thread has taken these locks in the
    opposite order, so a deadlock is one unlucky interleaving away."""


# -- process-wide sanitizer state ---------------------------------------------

_tls = threading.local()

#: guards _EDGES/_VIOLATIONS — a RAW lock, invisible to the sanitizer by
#: construction (it is never an SdLock)
_META_LOCK = threading.Lock()

#: held-name -> acquired-name -> first-witness record
_EDGES: dict[str, dict[str, dict[str, Any]]] = {}

#: every violation observed, raise-or-not (soaks assert this stays [])
_VIOLATIONS: list[dict[str, Any]] = []


def _state():
    if not hasattr(_tls, "held"):
        _tls.held = []   # _Held entries, acquisition order
        _tls.busy = False  # inside sanitizer bookkeeping: degrade to raw
    return _tls


def _stack() -> list[str]:
    # skip the two sanitizer frames (this helper + acquire)
    return [ln.rstrip("\n") for ln in
            traceback.format_stack(limit=_STACK_DEPTH)[:-2]]


def violations() -> list[dict[str, Any]]:
    """Copy of the violation ledger (the soak gates diff against [])."""
    with _META_LOCK:
        return [dict(v) for v in _VIOLATIONS]


def reset_sanitizer() -> None:
    """Tests: drop the order graph and the ledger. Per-thread held
    stacks are untouched (other threads own theirs)."""
    with _META_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()


def order_graph() -> dict[str, list[str]]:
    """name -> sorted successor names (introspection/tests)."""
    with _META_LOCK:
        return {a: sorted(bs) for a, bs in _EDGES.items()}


# -- telemetry handles (lazy: utils must stay importable before telemetry) ----

_FAMS: tuple | None = None


def declare_metrics() -> tuple:
    """Declare (or fetch) the ``sd_lock_*`` families — THE one
    definition: telemetry._declare_core calls this for the eager
    scrape-from-boot vocabulary and the sanitizer records through the
    same memoized handles, so the two can never drift (a divergent copy
    would raise the registry's re-declaration error instead)."""
    global _FAMS
    if _FAMS is None:
        from .. import telemetry
        from ..telemetry.registry import LOCK_BUCKETS

        _FAMS = (
            telemetry.histogram(
                "sd_lock_wait_seconds",
                "time contended sanitized-lock acquisitions waited "
                "(SD_LOCK_SANITIZER=1 runs only)",
                labels=("name",), buckets=LOCK_BUCKETS),
            telemetry.histogram(
                "sd_lock_hold_seconds",
                "how long each sanitized lock was held per acquisition",
                labels=("name",), buckets=LOCK_BUCKETS),
            telemetry.counter(
                "sd_lock_contended_total",
                "sanitized-lock acquisitions that found the lock held",
                labels=("name",)),
        )
    return _FAMS


_families = declare_metrics


class _Held:
    __slots__ = ("lock", "name", "stack", "count", "t0")

    def __init__(self, lock: "_SanitizedLock", stack: list[str]) -> None:
        self.lock = lock
        self.name = lock.name
        self.stack = stack
        self.count = 1
        self.t0 = time.perf_counter()


def _record_violation(report: dict[str, Any]) -> None:
    report["unix"] = round(time.time(), 3)
    report["thread"] = threading.current_thread().name
    with _META_LOCK:
        # bounded: a retry loop hammering the same violation must not
        # balloon the ledger (the soak gate only needs "non-empty + the
        # first witnesses"; 4096 distinct reports is already a bonfire)
        if len(_VIOLATIONS) < 4096:
            _VIOLATIONS.append(report)


class _SanitizedLock:
    """The sanitizer-on shape behind :func:`SdLock`. Non-reentrant."""

    reentrant = False

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = self._new_raw()

    def _new_raw(self):
        return threading.Lock()

    # -- bookkeeping ---------------------------------------------------------
    def _check_before_acquire(self, st) -> list[str]:
        """Re-acquisition + order-graph checks; returns the captured
        acquisition stack. Runs BEFORE any blocking so a would-be
        deadlock raises instead of hanging. Caller set ``st.busy``."""
        stack = _stack()
        for h in st.held:
            if h.lock is self:
                report = {
                    "kind": "reacquire", "lock": self.name,
                    "first_stack": h.stack, "second_stack": stack,
                }
                _record_violation(report)
                raise LockReacquireError(
                    f"non-reentrant lock '{self.name}' re-acquired by the "
                    f"thread already holding it (guaranteed self-deadlock "
                    f"with the sanitizer off)", report)
        held_names = {h.name: h for h in st.held}
        for held_name, h in held_names.items():
            if held_name == self.name:
                continue  # same-role hierarchy; instance case handled above
            with _META_LOCK:
                out = _EDGES.setdefault(held_name, {})
                if self.name in out:
                    continue  # edge already witnessed: nothing new to learn
                cycle = self._find_path(self.name, held_name)
                if cycle is None:
                    out[self.name] = {
                        "held_stack": h.stack, "acquire_stack": stack,
                        "thread": threading.current_thread().name,
                    }
                    continue
                witness = _EDGES.get(cycle[0], {}).get(cycle[1], {})
            report = {
                "kind": "order",
                "edge": [held_name, self.name],
                "cycle": [self.name, *cycle[1:]],
                "held_stack": h.stack,
                "acquire_stack": stack,
                "reverse_held_stack": witness.get("held_stack"),
                "reverse_acquire_stack": witness.get("acquire_stack"),
                "reverse_thread": witness.get("thread"),
            }
            _record_violation(report)
            raise LockOrderError(
                f"acquiring '{self.name}' while holding '{held_name}' "
                f"closes a lock-order cycle "
                f"({' -> '.join([held_name, self.name, *cycle[1:]])}): "
                f"another path already takes these locks in the opposite "
                f"order (both acquisition stacks in .report)", report)
        return stack

    @staticmethod
    def _find_path(src: str, dst: str) -> list[str] | None:
        """DFS over _EDGES (caller holds _META_LOCK): a name path
        src → … → dst, or None. The graph is bounded by the closed set
        of lock names, so this stays tiny."""
        seen = set()
        todo: list[tuple[str, tuple[str, ...]]] = [(src, (src,))]
        while todo:
            node, path = todo.pop()
            if node == dst:
                return list(path)
            if node in seen:
                continue
            seen.add(node)
            for nxt in _EDGES.get(node, ()):
                todo.append((nxt, path + (nxt,)))
        return None

    # -- the lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _state()
        if st.busy:
            # sanitizer-internal re-entry (telemetry's own family locks):
            # degrade to the raw primitive, no bookkeeping
            if not blocking:
                return self._lock.acquire(False)
            return self._lock.acquire(True, timeout)
        if not blocking:
            # a trylock can never deadlock: no re-acquisition or order
            # checks (raw semantics — a probe of a self-held Lock returns
            # False, and trylock-while-holding is the standard deadlock
            # AVOIDANCE pattern), no contention telemetry (a failed probe
            # is the caller's expected branch, not a convoy). A SUCCESSFUL
            # probe still pushes the held entry, so the hold is visible as
            # the held side of later blocking acquisitions' edges.
            reentered = next((h for h in st.held if h.lock is self), None) \
                if self.reentrant else None
            if not self._lock.acquire(False):
                return False
            if reentered is not None:
                reentered.count += 1
            else:
                st.busy = True
                try:
                    stack = _stack()
                finally:
                    st.busy = False
                st.held.append(_Held(self, stack))
            return True
        st.busy = True
        try:
            reentered = None
            if self.reentrant:
                reentered = next(
                    (h for h in st.held if h.lock is self), None)
            stack = None if reentered is not None \
                else self._check_before_acquire(st)
        finally:
            st.busy = False
        got = self._lock.acquire(False)
        if not got:
            st.busy = True
            try:
                wait_h, _hold_h, contended_c = _families()
                contended_c.inc(name=self.name)
            finally:
                st.busy = False
            t0 = time.perf_counter()
            got = self._lock.acquire(True, timeout)
            if got:
                st.busy = True
                try:
                    wait_h.observe(time.perf_counter() - t0, name=self.name)
                finally:
                    st.busy = False
        if got:
            if reentered is not None:
                reentered.count += 1
            else:
                st.held.append(_Held(self, stack))
        return got

    def release(self) -> None:
        st = _state()
        if st.busy:
            self._lock.release()
            return
        entry = None
        for i in range(len(st.held) - 1, -1, -1):
            if st.held[i].lock is self:
                entry = st.held[i]
                if entry.count > 1:
                    entry.count -= 1
                    entry = None
                else:
                    del st.held[i]
                break
        self._lock.release()
        if entry is not None:
            st.busy = True
            try:
                _families()[1].observe(
                    time.perf_counter() - entry.t0, name=self.name)
            finally:
                st.busy = False

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class _SanitizedRLock(_SanitizedLock):
    """Sanitizer-on shape behind :func:`SdRLock`: same-thread
    re-acquisition is legal (counted, no new edges); everything else —
    order graph, telemetry — behaves like :class:`_SanitizedLock`."""

    reentrant = True

    def _new_raw(self):
        return threading.RLock()

    def locked(self) -> bool:  # RLock has no .locked() before 3.14
        raise AttributeError("SdRLock does not expose locked()")


def SdLock(name: str):
    """A named mutex. Disabled (the default): the bare
    ``threading.Lock()`` — zero wrapper cost. ``SD_LOCK_SANITIZER=1``
    (read at creation): a sanitized lock feeding the held-stack /
    order-graph / telemetry machinery above."""
    if sanitizer_enabled():
        return _SanitizedLock(name)
    return threading.Lock()


def SdRLock(name: str):
    """Named re-entrant mutex; same enablement contract as SdLock."""
    if sanitizer_enabled():
        return _SanitizedRLock(name)
    return threading.RLock()
