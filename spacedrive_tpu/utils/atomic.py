"""Crash-safe artifact writes: the tempfile → fsync → rename discipline.

A user-visible artifact (backup, thumbnail, trace export, config sidecar)
must never be observable half-written: a process kill or a full disk
mid-`write()` would otherwise leave a torn file that poisons every later
reader (a backup that fails validation, a thumbnail that renders as
garbage, a JSONL export whose tail line is cut mid-record).

The discipline, applied by every writer in this module:

1. write the complete payload to a temporary file **in the destination
   directory** (same filesystem, so the rename is atomic);
2. ``fsync`` the temp file (the data is durable before the name exists);
3. ``os.replace`` it over the destination (atomic on POSIX);
4. best-effort ``fsync`` the directory (the *rename* is durable too).

A kill at any point leaves either the old artifact or the new one —
never a hybrid — plus at worst one stale ``*.sd-tmp*`` file, which
:func:`cleanup_stale_tmp` sweeps on the next boot.

The sdlint ``durability-discipline`` pass keeps artifact writers in
objects|backups|telemetry|preferences on this helper (or explicitly
waived) — see docs/static-analysis.md.
"""

from __future__ import annotations

import contextlib
import logging
import os
import uuid
from pathlib import Path
from typing import Iterator

logger = logging.getLogger(__name__)

#: infix every temp file carries so stale ones are recognizable at boot
TMP_MARK = ".sd-tmp"


def _fsync_dir(directory: Path) -> None:
    """Durable rename: fsync the directory entry (best-effort — some
    filesystems refuse O_RDONLY dir fds; the file data is already safe)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_path(dest: str | Path) -> Iterator[Path]:
    """Yield a temp path next to ``dest``; on clean exit fsync it and
    rename it into place, on exception unlink it. For writers that need a
    *path* (PIL ``save``, native encoders), not a file object."""
    dest = Path(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp = dest.parent / f"{dest.name}{TMP_MARK}.{uuid.uuid4().hex[:8]}"
    try:
        yield tmp
        if tmp.exists():
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        os.replace(tmp, dest)
        _fsync_dir(dest.parent)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise


def atomic_write_bytes(dest: str | Path, data: bytes) -> None:
    dest = Path(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp = dest.parent / f"{dest.name}{TMP_MARK}.{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        # chaos seam AT the discipline's crash window: the temp is fully
        # written and durable but the destination name does not exist yet —
        # a kill/enospc here is the exact torn-write moment the
        # tempfile→rename contract defends against
        from .. import faults

        faults.inject("artifact_write", key=dest.name)
        os.replace(tmp, dest)
        _fsync_dir(dest.parent)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise


def atomic_write_text(dest: str | Path, text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(dest, text.encode(encoding))


def append_line(dest: str | Path, line: str) -> None:
    """Concurrent-writer-safe JSONL append: one ``O_APPEND`` ``write()``
    plus fsync. POSIX serializes O_APPEND writes — the kernel moves the
    offset and writes atomically per call — so two bench runs appending
    to ``BENCH_history.jsonl`` at once interleave whole lines, never
    bytes. The tempfile→rename discipline above is wrong for appends (it
    would clobber the other writer's line); this is the append-shaped
    half of the same crash-safety contract: a kill mid-call loses at
    most this one line, never corrupts earlier ones."""
    dest = Path(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    data = (line.rstrip("\n") + "\n").encode()
    fd = os.open(dest, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)


def cleanup_stale_tmp(directory: str | Path) -> int:
    """Remove ``*.sd-tmp*`` leftovers a kill stranded mid-write (called at
    boot for artifact dirs); returns how many were removed. Scans the
    directory AND one subdirectory level — sharded artifact dirs (the
    thumbnail cache's 2-hex shards) keep their temps one level down."""
    directory = Path(directory)
    removed = 0
    try:
        entries = list(directory.glob(f"*{TMP_MARK}*")) \
            + list(directory.glob(f"*/*{TMP_MARK}*"))
    except OSError:
        return 0
    for stale in entries:
        try:
            if stale.is_dir():
                import shutil

                shutil.rmtree(stale, ignore_errors=True)
            else:
                stale.unlink(missing_ok=True)
            removed += 1
        except OSError:
            logger.debug("could not remove stale temp %s", stale)
    if removed:
        logger.info("removed %d stale temp artifact(s) under %s",
                    removed, directory)
    return removed
