"""Opportunistic device recapture: stop gating measurement on bench timing.

The relay has died mid-round twice, and every kernel built since has gone
unmeasured because the only thing that ever ran the device suite was a
human-triggered bench that happened to start while the relay was up. This
module inverts that: a daemon thread polls ``jax_guard.relay_listening()``
(a sub-second TCP check) and, on the FIRST recovery it observes, runs the
device bench suite in a fresh subprocess and writes the record to
``BENCH_device_opportunistic.json`` — so a relay that comes back at 3am
still produces device numbers for the round.

One-shot by design: the prize is *a* measurement, not a monitor. The
subprocess matters — this process may already be pinned to the CPU platform
(jax_guard) or hold a dead backend; a fresh interpreter probes and inits
cleanly. Consumers:

- ``bench.py``: starts a watcher when its device probe fails, so a relay
  recovering mid-run (the combined suite runs for many minutes) is caught.
- ``node.Node``: starts a watcher at boot when ``SD_OPPORTUNISTIC_BENCH``
  is set and the accelerator probe came up empty — long-lived nodes are the
  best vantage point for an eventual recovery.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable

logger = logging.getLogger(__name__)

#: where the opportunistic record lands (next to the other BENCH_*.json)
DEFAULT_OUT = Path(__file__).resolve().parents[2] / "BENCH_device_opportunistic.json"

#: poll cadence; SD_OPPORTUNISTIC_INTERVAL overrides (tests use ~0.05s)
DEFAULT_INTERVAL = 30.0


def poll_interval() -> float:
    raw = os.environ.get("SD_OPPORTUNISTIC_INTERVAL", "").strip()
    try:
        return max(0.01, float(raw)) if raw else DEFAULT_INTERVAL
    except ValueError:
        return DEFAULT_INTERVAL


def run_device_suite(timeout: float = 1800.0) -> dict:
    """Run the device-resident kernel bench in a fresh subprocess and return
    its JSON record. Scrubs the parent's probe verdict (this process decided
    'cpu' before the relay recovered — the child must re-probe) and caps the
    recovery-wait (the relay is listening, so a long window is pointless)."""
    env = dict(os.environ)
    for key in ("SD_BENCH_DEVICE_VERDICT", "SD_BENCH_DEVICE_REASON"):
        env.pop(key, None)
    env["SD_BENCH_MODE"] = "device_kernel"
    env.setdefault("SD_BENCH_RELAY_WAIT", "30")
    bench = Path(__file__).resolve().parents[2] / "bench.py"
    proc = subprocess.run([sys.executable, str(bench)], env=env,
                          capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"device suite exited {proc.returncode}: {proc.stderr[-500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


class RelayRecaptureWatcher:
    """Poll relay liveness; on first recovery run ``on_recover`` once and
    persist its record. Thread-safe start/stop; safe to stop before, during
    or after recovery."""

    def __init__(self, on_recover: Callable[[], dict] | None = None,
                 interval: float | None = None,
                 out_path: str | Path | None = None) -> None:
        self.on_recover = on_recover or run_device_suite
        self.interval = poll_interval() if interval is None else interval
        self.out_path = Path(out_path) if out_path else DEFAULT_OUT
        self.recovered = False
        #: True while the one-shot capture (bench subprocess) is running —
        #: owners consult this at shutdown to wait for an in-flight
        #: measurement instead of abandoning it (the whole point of the
        #: watcher) when the daemon thread would die with the process
        self.capturing = False
        self.record: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "RelayRecaptureWatcher":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sd-relay-recapture")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        from .jax_guard import relay_listening

        while not self._stop.is_set():
            alive = False
            try:
                alive = relay_listening()
            except Exception:
                logger.exception("relay liveness poll failed")
            if alive:
                self._recapture()
                return
            self._stop.wait(self.interval)

    def _recapture(self) -> None:
        logger.info("relay recovered — running opportunistic device suite")
        # the recovery is an operator-visible event, not only a log line:
        # counter + telemetry event before the (long) capture starts
        try:
            from .. import telemetry

            telemetry.counter(
                "sd_relay_recovered_total",
                "relay recoveries observed by the recapture watcher").inc()
            telemetry.event("relay.recovered",
                            out_path=str(self.out_path))
        except Exception:
            logger.exception("could not record relay recovery telemetry")
        # the device is back: hybrid hashers that a mid-batch wedge degraded
        # to native CPU re-probe both engines on their next batch (the
        # restore half of the degradation ladder, robustness.md)
        try:
            from ..objects.hasher import reset_device_verdicts

            reset_device_verdicts()
        except Exception:
            logger.exception("could not reset hybrid hasher verdicts")
        self.capturing = True
        try:
            record = dict(self.on_recover() or {})
        except Exception:
            logger.exception("opportunistic device suite failed; the relay "
                             "may have died again mid-measurement")
            return
        finally:
            self.capturing = False
        record.setdefault("captured_unix", round(time.time(), 1))
        record.setdefault("trigger", "opportunistic-relay-recapture")
        try:
            self.out_path.write_text(json.dumps(record) + "\n")
        except OSError:
            logger.exception("could not write %s", self.out_path)
        self.record = record
        self.recovered = True
        logger.info("opportunistic device record written to %s", self.out_path)
