"""Logger bootstrap: rotating file appender + stdout, env-filtered.

Reference: Node::init_logger (core/src/lib.rs:137-194) — daily-rotated
non-blocking file appender (sd.log, keep 4) plus a stdout layer with an
EnvFilter (RUST_LOG), and a global panic hook logging file:line. Here:
TimedRotatingFileHandler (midnight, backupCount=4) under <data_dir>/logs,
stdout at SD_LOG level (module overrides via "module=LEVEL" segments, the
EnvFilter syntax subset), and sys.excepthook logging uncaught exceptions.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
from pathlib import Path

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"
_installed = False


def init_logger(data_dir: str | Path, level: str | None = None) -> None:
    """Idempotent; SD_LOG examples: "INFO", "DEBUG",
    "INFO,spacedrive_tpu.locations=DEBUG"."""
    global _installed
    if _installed:
        return
    _installed = True

    spec = level or os.environ.get("SD_LOG", "INFO")
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "INFO"
    overrides: list[tuple[str, str]] = []
    for part in parts:
        if "=" in part:
            module, _, lvl = part.partition("=")
            overrides.append((module.strip(), lvl.strip().upper()))
        else:
            root_level = part.upper()

    pkg_logger = logging.getLogger("spacedrive_tpu")
    pkg_logger.setLevel(getattr(logging, root_level, logging.INFO))
    for module, lvl in overrides:
        logging.getLogger(module).setLevel(getattr(logging, lvl, logging.INFO))

    formatter = logging.Formatter(_FORMAT)

    log_dir = Path(data_dir) / "logs"
    try:
        log_dir.mkdir(parents=True, exist_ok=True)
        file_handler = logging.handlers.TimedRotatingFileHandler(
            log_dir / "sd.log", when="midnight", backupCount=4,
            encoding="utf-8", delay=True)
        file_handler.setFormatter(formatter)
        pkg_logger.addHandler(file_handler)
    except OSError as e:
        logging.getLogger(__name__).warning("no file logging: %s", e)

    # exact-type check: FileHandler subclasses StreamHandler, and a host
    # app's file handler must not suppress the stdout layer
    if not any(type(h) is logging.StreamHandler
               for h in logging.getLogger().handlers):
        stream = logging.StreamHandler()
        stream.setFormatter(formatter)
        logging.getLogger().addHandler(stream)

    # panic-hook analogue (lib.rs:181-191): uncaught exceptions hit the log
    previous = sys.excepthook

    def hook(exc_type, exc, tb):
        if exc_type is not KeyboardInterrupt:
            pkg_logger.critical("uncaught exception", exc_info=(exc_type, exc, tb))
        previous(exc_type, exc, tb)

    sys.excepthook = hook
