"""Logger bootstrap: rotating file appender + stdout, env-filtered.

Reference: Node::init_logger (core/src/lib.rs:137-194) — daily-rotated
non-blocking file appender (sd.log, keep 4) plus a stdout layer with an
EnvFilter (RUST_LOG), and a global panic hook logging file:line. Here:
TimedRotatingFileHandler (midnight, backupCount=4) under <data_dir>/logs,
stdout at SD_LOG level (module overrides via "module=LEVEL" segments, the
EnvFilter syntax subset), and sys.excepthook logging uncaught exceptions.

Re-init semantics (ISSUE 5 satellite): ``init_logger`` is idempotent per
``data_dir`` — calling it again with the SAME directory is a no-op, but a
DIFFERENT directory swaps the file appender over (the old handler is
closed and removed). The previous module-global ``_installed`` flag
silently ignored the second call, so a second library open (and every
test after the first) kept logging into the first library's directory.
``reset_for_tests()`` tears the installation down completely.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
import threading
from pathlib import Path

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"

_LOCK = threading.Lock()
#: installed state: the data_dir the file handler writes under, the
#: handler itself, and whether the stdout layer / excepthook are wired
_STATE: dict = {"data_dir": None, "file_handler": None,
                "stream_handler": None, "hook_prev": None,
                "hooks_installed": False}


def init_logger(data_dir: str | Path, level: str | None = None) -> None:
    """Idempotent per data_dir; SD_LOG examples: "INFO", "DEBUG",
    "INFO,spacedrive_tpu.locations=DEBUG". A call with a different
    ``data_dir`` re-targets the file appender (second library open,
    tests)."""
    data_dir = Path(data_dir)
    pkg_logger = logging.getLogger("spacedrive_tpu")
    with _LOCK:
        if _STATE["data_dir"] == data_dir:
            return

        spec = level or os.environ.get("SD_LOG", "INFO")
        parts = [p.strip() for p in spec.split(",") if p.strip()]
        root_level = "INFO"
        overrides: list[tuple[str, str]] = []
        for part in parts:
            if "=" in part:
                module, _, lvl = part.partition("=")
                overrides.append((module.strip(), lvl.strip().upper()))
            else:
                root_level = part.upper()

        pkg_logger.setLevel(getattr(logging, root_level, logging.INFO))
        for module, lvl in overrides:
            logging.getLogger(module).setLevel(
                getattr(logging, lvl, logging.INFO))

        formatter = logging.Formatter(_FORMAT)

        # build the NEW appender first; the working one is only swapped
        # out once its replacement exists, and a failed target (unwritable
        # dir) leaves state untouched so a later call retries instead of
        # leaving the process with no file logging at all
        new_handler = None
        log_dir = data_dir / "logs"
        try:
            log_dir.mkdir(parents=True, exist_ok=True)
            new_handler = logging.handlers.TimedRotatingFileHandler(
                log_dir / "sd.log", when="midnight", backupCount=4,
                encoding="utf-8", delay=True)
            new_handler.setFormatter(formatter)
        except OSError as e:
            logging.getLogger(__name__).warning("no file logging: %s", e)
        if new_handler is not None:
            old = _STATE["file_handler"]
            if old is not None:
                pkg_logger.removeHandler(old)
                try:
                    old.close()
                except Exception:
                    pass
            pkg_logger.addHandler(new_handler)
            _STATE["file_handler"] = new_handler
            _STATE["data_dir"] = data_dir

        if _STATE["hooks_installed"]:
            return
        _STATE["hooks_installed"] = True

        # stdout layer + panic hook install exactly once per process
        # exact-type check: FileHandler subclasses StreamHandler, and a host
        # app's file handler must not suppress the stdout layer
        if not any(type(h) is logging.StreamHandler
                   for h in logging.getLogger().handlers):
            stream = logging.StreamHandler()
            stream.setFormatter(formatter)
            logging.getLogger().addHandler(stream)
            _STATE["stream_handler"] = stream

        # panic-hook analogue (lib.rs:181-191): uncaught exceptions hit
        # the log
        previous = sys.excepthook
        _STATE["hook_prev"] = previous

        def hook(exc_type, exc, tb):
            if exc_type is not KeyboardInterrupt:
                pkg_logger.critical("uncaught exception",
                                    exc_info=(exc_type, exc, tb))
            previous(exc_type, exc, tb)

        sys.excepthook = hook


def installed_data_dir() -> Path | None:
    """The directory the file appender currently writes under (tests)."""
    with _LOCK:
        return _STATE["data_dir"]


def reset_for_tests() -> None:
    """Tear the installation down: remove + close the handlers, restore
    the excepthook, forget the data_dir so the next init_logger installs
    fresh."""
    pkg_logger = logging.getLogger("spacedrive_tpu")
    with _LOCK:
        fh = _STATE["file_handler"]
        if fh is not None:
            pkg_logger.removeHandler(fh)
            try:
                fh.close()
            except Exception:
                pass
        sh = _STATE["stream_handler"]
        if sh is not None:
            logging.getLogger().removeHandler(sh)
        if _STATE["hook_prev"] is not None:
            sys.excepthook = _STATE["hook_prev"]
        _STATE.update(data_dir=None, file_handler=None,
                      stream_handler=None, hook_prev=None,
                      hooks_installed=False)
