"""Wedge-proof jax access: one guarded answer per process.

The tunneled device platform plugin HANGS (not errors) when its relay
dies, and it forces device backend init regardless of ``JAX_PLATFORMS`` —
so an unguarded ``jax.devices()``/``device_put`` inside a job parks the
single job worker forever and every queued scan behind it (observed live:
a chained dedup_detector wedging the whole pipeline).

``ensure_jax_safe()`` is the gate every production device touchpoint calls
before its first jax use:

- if this process is already pinned to the CPU platform (tests, bench
  fallback), jax cannot wedge — return immediately;
- otherwise probe backend init once in a deadline-bounded subprocess;
- on probe failure/timeout, pin THIS process to the CPU backend (the
  plugin honors a live ``jax.config`` update) so all later jax use runs
  on CPU instead of hanging.

Returns True when the device backend is usable, False when the process
was pinned to CPU. Either way, jax is safe to call afterwards.

Scope: this is FIRST-TOUCH protection. Once a device backend is
initialized in-process, a relay that dies later hangs the next device op
regardless of any guard — that cannot be fixed at this layer without
wrapping every op in a watchdog. The memoized verdict matches that
reality: short-lived consumers (bench children) may seed it; long-lived
nodes let the first device-touching job probe at its own moment.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

_STATE = {"checked": False, "device_ok": False}
_LOCK = threading.Lock()

#: device backend init on the healthy tunnel takes ~10-20s; a wedged relay
#: never returns, so the probe needs real headroom without stalling a scan
#: for minutes
PROBE_TIMEOUT = float(os.environ.get("SD_JAX_PROBE_TIMEOUT", "75"))

#: the tunnel's loopback relay listens on these local ports; when the
#: relay process is dead every connect is REFUSED instantly, which turns
#: "is the device reachable at all" into a sub-second check instead of a
#: 75s subprocess deadline (observed: the round-4 relay death mode is
#: no-listener, not accept-and-hang)
_DEFAULT_RELAY_PORTS = (8082, 8083, 8087, 8092)


def _relay_ports_from_env(raw: str | None) -> tuple[int, ...]:
    """``SD_RELAY_PORTS=8082,8083`` overrides the hardcoded tuple (parsed
    at import, like SD_JAX_PROBE_TIMEOUT above) so a relay deployed on
    different ports degrades to the slow-but-correct subprocess probe
    instead of a false instant "no listener → pin to CPU" verdict."""
    if not raw:
        return _DEFAULT_RELAY_PORTS
    ports: list[int] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            port = int(part)
        except ValueError:
            logger.warning("SD_RELAY_PORTS: ignoring non-integer %r", part)
            continue
        if 0 < port < 65536:
            ports.append(port)
        else:
            logger.warning("SD_RELAY_PORTS: ignoring out-of-range %d", port)
    if not ports:
        logger.warning("SD_RELAY_PORTS=%r has no usable ports; keeping "
                       "defaults %s", raw, _DEFAULT_RELAY_PORTS)
        return _DEFAULT_RELAY_PORTS
    return tuple(ports)


RELAY_PORTS = _relay_ports_from_env(os.environ.get("SD_RELAY_PORTS"))


def relay_listening(timeout_s: float = 1.5) -> bool:
    """True when any relay port accepts a TCP connect — the relay process
    is alive (the far side may still be wedged; only the full backend
    probe proves end-to-end health). False means no listener: the device
    is certainly unreachable and the slow probe can be skipped."""
    import socket

    for port in RELAY_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout_s):
                return True
        except OSError:
            continue
    return False


def _relay_up_with_retry() -> bool:
    """``relay_listening()`` with a short flap-absorbing retry: a relay
    mid-restart (or an accept queue briefly overflowing) refuses a single
    connect, and one refused probe must not pin a long-lived node to CPU.
    Only a probe that stays refused across the whole jittered window
    counts as down."""
    from .. import faults, telemetry
    from .retry import RetryPolicy, is_relay_flap, retry_call

    outcomes = telemetry.counter(
        "sd_relay_probe_total", "relay liveness probes by outcome",
        labels=("outcome",))

    def probe() -> None:
        faults.inject("relay_probe")
        if not relay_listening():
            raise ConnectionRefusedError("relay ports refused")

    try:
        retry_call(probe,
                   policy=RetryPolicy(attempts=3, base_s=0.1, max_s=0.4,
                                      jitter=0.5, budget_s=2.0),
                   classify=is_relay_flap, label="relay-probe")
        outcomes.inc(outcome="up")
        return True
    except ConnectionError:
        outcomes.inc(outcome="down")
        return False


def seed(device_ok: bool) -> None:
    """Record a definitive probe outcome obtained elsewhere (the node's
    boot-time accelerator probe) so the first job doesn't re-pay the
    subprocess probe. A False seed pins the process to CPU immediately."""
    with _LOCK:
        if _STATE["checked"]:
            return
        if not device_ok:
            try:
                import jax

                jax.config.update("jax_platforms", "cpu")
            except Exception:
                logger.exception("could not pin jax to CPU")
        _STATE.update(checked=True, device_ok=device_ok)


def ensure_jax_safe(timeout: float | None = None) -> bool:
    with _LOCK:
        if _STATE["checked"]:
            return _STATE["device_ok"]
        # probe-once guard: the lock EXISTS to make every other caller
        # wait for the single device probe (robustness.md known waivers)
        ok = _probe(PROBE_TIMEOUT if timeout is None else timeout)  # lint: ok(hold-blocking)
        _STATE.update(checked=True, device_ok=ok)
        return ok


def _probe(timeout: float) -> bool:
    try:
        import jax

        # already pinned to CPU (tests/bench fallback): cannot wedge
        platforms = jax.config.jax_platforms
        if platforms and set(str(platforms).split(",")) <= {"cpu"}:
            return False
    except Exception:
        return False
    if os.environ.get("SD_ASSUME_DEVICE_OK"):
        return True
    if not _relay_up_with_retry():
        logger.warning("relay ports refused — device unreachable; pinning "
                       "this process to the CPU platform (fast-path, no "
                       "%.0fs probe paid)", timeout)
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            logger.exception("could not pin jax to CPU; jax use may hang")
        return False

    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout)
        if probe.returncode == 0:
            return True
        reason = probe.stderr.decode(errors="replace")[-200:]
    except subprocess.TimeoutExpired:
        reason = f"backend init exceeded {timeout:.0f}s (relay wedged?)"
    logger.warning("device backend unusable (%s); pinning this process to "
                   "the CPU platform so jax cannot wedge", reason.strip())
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        logger.exception("could not pin jax to CPU; jax use may hang")
    return False
