"""Static-rigor gate — THIN SHIM over ``spacedrive_tpu.analysis``.

The 135-line stdlib AST linter that lived here grew into the multi-pass
framework in ``spacedrive_tpu/analysis/`` (pass manager, per-pass
waivers, baseline ratchet, and the jax wedge-safety / async-hygiene /
concurrency passes). This module keeps the original entry points —
``check_file``/``check_tree``/``python -m spacedrive_tpu.utils.lint`` —
running the ORIGINAL defect classes (unused imports, bare excepts,
duplicate top-level defs, syntax errors) with the original message
format, so existing callers and tests see identical behavior.

For the full pass list run ``python -m spacedrive_tpu.analysis``.
"""

from __future__ import annotations

import sys
from pathlib import Path

WAIVER = "# lint: ok"


def _manager(root: Path):
    from ..analysis.engine import PassManager
    from ..analysis.passes.legacy import LEGACY_PASSES

    return PassManager([cls() for cls in LEGACY_PASSES], root)


def check_file(path: Path) -> list[str]:
    path = Path(path)
    findings = _manager(path.parent).check_file(path)
    return [f"{f.path}:{f.lineno}: {f.message}" for f in findings]


def check_tree(root: Path) -> list[str]:
    problems: list[str] = []
    manager = _manager(root)
    for f in manager.check_tree():
        problems.append(f"{f.path}:{f.lineno}: {f.message}")
    return problems


def main() -> int:
    root = Path(sys.argv[1]) if len(sys.argv) > 1 \
        else Path(__file__).resolve().parents[1]
    problems = check_tree(root)
    for p in problems:
        print(p)
    print(f"{len(problems)} finding(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
