"""Static-rigor gate (SURVEY §5.2): a stdlib AST linter the test suite runs.

The reference's rigor layer is clippy + rustc's own analysis; this image
ships no Python linters, so the gate is built from ``ast``: syntax (via
compile), unused imports, duplicate top-level definitions, and bare
``except:`` clauses — the defect classes that actually bite a long-lived
codebase. ``# lint: ok`` on the offending line waives a finding (the
escape hatch for deliberate re-exports and probe-style excepts).

Run: ``python -m spacedrive_tpu.utils.lint`` (or via tests/test_lint.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

WAIVER = "# lint: ok"


import re as _re

_IDENT = _re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()

    def add_annotation_strings(node: ast.AST | None) -> None:
        # quoted annotations ("Library") reference names the AST only sees
        # as string constants — count their identifiers as used
        for sub in ast.walk(node) if node is not None else ():
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                used.update(_IDENT.findall(sub.value))

    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_annotation_strings(node.returns)
            for arg in (node.args.args + node.args.posonlyargs
                        + node.args.kwonlyargs
                        + ([node.args.vararg] if node.args.vararg else [])
                        + ([node.args.kwarg] if node.args.kwarg else [])):
                add_annotation_strings(arg.annotation)
        elif isinstance(node, ast.AnnAssign):
            add_annotation_strings(node.annotation)
    return used


def check_file(path: Path) -> list[str]:
    src = path.read_text()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    def waived(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and WAIVER in lines[lineno - 1]

    problems: list[str] = []
    used = _used_names(tree)
    # module __all__ / docstring re-export patterns count as use
    exported: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "__all__"
                and isinstance(node.value, (ast.List, ast.Tuple))):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    exported.add(elt.value)

    is_package_init = path.name == "__init__.py"
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if getattr(node, "module", None) == "__future__":
                continue
            for alias in node.names:
                name = (alias.asname or alias.name).split(".")[0]
                if alias.name == "*" or waived(node.lineno):
                    continue
                if name in used or name in exported:
                    continue
                if is_package_init:  # packages re-export by importing
                    continue
                problems.append(f"{path}:{node.lineno}: unused import "
                                f"'{alias.asname or alias.name}'")
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            if not waived(node.lineno):
                problems.append(f"{path}:{node.lineno}: bare 'except:' "
                                "(catch Exception or narrower)")

    # duplicate top-level defs shadow silently
    seen: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen and not waived(node.lineno):
                problems.append(
                    f"{path}:{node.lineno}: duplicate top-level definition "
                    f"'{node.name}' (first at line {seen[node.name]})")
            seen.setdefault(node.name, node.lineno)
    return problems


def check_tree(root: Path) -> list[str]:
    problems: list[str] = []
    for path in sorted(root.rglob("*.py")):
        if "_build" in path.parts or ".bench_cache" in path.parts:
            continue
        problems.extend(check_file(path))
    return problems


def main() -> int:
    root = Path(sys.argv[1]) if len(sys.argv) > 1 \
        else Path(__file__).resolve().parents[1]
    problems = check_tree(root)
    for p in problems:
        print(p)
    print(f"{len(problems)} finding(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
