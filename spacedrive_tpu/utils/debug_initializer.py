"""Debug initializer: declarative dev fixtures applied at boot.

Reference: core/src/util/debug_initializer.rs:32-56 — an `sd_init.json`
(path from SD_INIT_DATA, :79, else `<data_dir>/sd_init.json`) declares
libraries and locations to ensure/reset on startup; upstream uses it as the
de-facto e2e harness, and the server shell tests here do the same.

Schema:
{
  "libraries": [
    {"name": "dev", "reset_on_startup": false,
     "locations": [{"path": "/tmp/tree", "scan": true, "hasher": "hybrid"}]}
  ]
}
"""

from __future__ import annotations

import json
import logging
import os
from pathlib import Path
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from ..node import Node

logger = logging.getLogger(__name__)


def init_config_path(data_dir: str | Path) -> Path | None:
    env = os.environ.get("SD_INIT_DATA")
    if env:
        return Path(env)
    default = Path(data_dir) / "sd_init.json"
    return default if default.exists() else None


def apply(node: "Node") -> None:
    """Idempotent: existing libraries/locations are reused unless
    reset_on_startup asks for a clean slate (debug_initializer.rs:40-52)."""
    path = init_config_path(node.data_dir)
    if path is None:
        return
    try:
        config: dict[str, Any] = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("sd_init.json unreadable (%s); skipping fixtures", e)
        return

    from ..locations import create_location, scan_location
    from ..models import Location

    for spec in config.get("libraries", []):
        name = spec.get("name") or "debug"
        existing = [lib for lib in node.libraries.list()
                    if lib.config.get("name") == name]
        if existing and spec.get("reset_on_startup"):
            logger.info("sd_init: resetting library %r", name)
            for lib in existing:
                node.libraries.delete(lib.id)
            existing = []
        library = existing[0] if existing else node.libraries.create(name)
        for loc_spec in spec.get("locations", []):
            loc_path = Path(loc_spec["path"])
            if not loc_path.is_dir():
                logger.warning("sd_init: location path missing: %s", loc_path)
                continue
            row = None
            for candidate in library.db.find(Location):
                if candidate["path"] and Path(candidate["path"]) == loc_path.resolve():
                    row = candidate
                    break
            if row is None:
                try:
                    row = create_location(
                        library, loc_path,
                        name=loc_spec.get("name"),
                        hasher=loc_spec.get("hasher", "hybrid"))
                except Exception as e:
                    logger.warning("sd_init: create_location(%s): %s", loc_path, e)
                    continue
            if loc_spec.get("scan"):
                try:
                    scan_location(library, row["id"])
                except Exception:
                    logger.exception("sd_init: scan failed for %s", loc_path)
        logger.info("sd_init: library %r ready (%d locations)", name,
                    len(spec.get("locations", [])))
