"""Misc infrastructure: versioned-JSON migrator, version manager, helpers."""

from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    """Knob parse that can never take a subsystem down: a malformed value
    degrades to the default (`server/pool.configured_workers` set the
    precedent — a typo'd knob must not abort startup or crash-loop)."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
