"""Misc infrastructure: versioned-JSON migrator, version manager, helpers."""
