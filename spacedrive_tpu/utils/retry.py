"""The one retry-with-backoff policy (and the transient-error taxonomy).

Every transient-failure class in the system retries through
:func:`retry_call` with an explicit :class:`RetryPolicy` — never an ad-hoc
``time.sleep`` loop (the ``retry-discipline`` sdlint pass enforces this in
jobs|objects|sync|p2p). Three properties the scattered loops never had:

- **budgeted**: attempts AND total wall time are bounded, so a permanently
  failing dependency degrades to its caller's fatal path instead of
  stalling a lane;
- **jittered exponential backoff**: concurrent retriers (pipeline stages,
  lanes) decorrelate instead of thundering back in lockstep;
- **pause/cancel-aware**: the backoff sleeps in poll quanta and runs
  ``cancel_check`` between quanta, so a worker whose ``check_commands``
  raises JobPaused/JobCanceled unwinds within one poll interval instead of
  sleeping out the window.

Classification (transient vs fatal) lives here too so every layer agrees:
SQLITE_BUSY, EINTR/EIO/EAGAIN reads, and connection flaps are retryable;
vanished/permission-denied/truncated items are NOT — those quarantine at
the item level (docs/architecture/robustness.md has the full taxonomy).
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import random
import sqlite3
import time
from typing import Any, Callable

from .. import telemetry

logger = logging.getLogger(__name__)

#: backoff sleep quantum — also the worst-case latency for a pause/cancel
#: arriving mid-backoff (matches the pipeline executor's poll cadence)
POLL_S = 0.05


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """attempts counts CALLS (attempts=1 → no retry); budget_s bounds the
    total time spent waiting between them."""

    attempts: int = 3
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    #: +/- fraction of the delay drawn uniformly (0.5 → 50%..150%)
    jitter: float = 0.5
    budget_s: float = 10.0

    def delay(self, retry_index: int, rng: random.Random) -> float:
        d = min(self.max_s, self.base_s * self.multiplier ** retry_index)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


# -- the transient-vs-fatal taxonomy ------------------------------------------

#: OSError errnos that mean "the same call can succeed if repeated"
TRANSIENT_ERRNOS = frozenset({errno.EINTR, errno.EIO, errno.EAGAIN,
                              errno.EBUSY})


def is_sqlite_busy(exc: BaseException) -> bool:
    """SQLITE_BUSY/SQLITE_LOCKED surface as OperationalError text."""
    if not isinstance(exc, sqlite3.OperationalError):
        return False
    msg = str(exc).lower()
    return "locked" in msg or "busy" in msg


def is_transient_io(exc: BaseException) -> bool:
    return isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS


def is_relay_flap(exc: BaseException) -> bool:
    """A refused/reset/timed-out probe of a service known to flap (the
    device relay, a peer link) — retry before declaring it down."""
    return isinstance(exc, (ConnectionError, TimeoutError))


def is_transient(exc: BaseException) -> bool:
    """The union class: what retry_call retries by default. Exceptions can
    also self-classify via an ``sd_transient`` attribute (injected crash
    markers, wedge errors)."""
    return (is_sqlite_busy(exc) or is_transient_io(exc)
            or is_relay_flap(exc) or getattr(exc, "sd_transient", False))


def is_device_wedge(exc: BaseException) -> bool:
    """Device-backend failures that the hasher degradation ladder absorbs
    (device → native CPU): the injected wedge marker or anything raised
    out of the jax/jaxlib runtime."""
    if getattr(exc, "sd_transient", False) and "wedge" in type(exc).__name__.lower():
        return True
    return type(exc).__module__.split(".")[0] in ("jax", "jaxlib")


# -- process-wide accounting (telemetry registry) ------------------------------
# PR 4's bespoke module-global stats dict is gone: retry accounting lives on
# the unified registry (chaos benches read the deltas from a telemetry
# snapshot — sd_retry_attempts_total / sd_retry_backoff_seconds_total /
# sd_retry_gave_up_total).

_ATTEMPTS = telemetry.counter(
    "sd_retry_attempts_total",
    "re-calls made after a transient failure (utils/retry.py)")
_BACKOFF_S = telemetry.counter(
    "sd_retry_backoff_seconds_total",
    "total wall time spent in retry backoff")
_GAVE_UP = telemetry.counter(
    "sd_retry_gave_up_total",
    "retry budgets exhausted (attempts or wall budget)")


# -- the driver ----------------------------------------------------------------

def retry_call(fn: Callable[[], Any], *,
               policy: RetryPolicy,
               classify: Callable[[BaseException], bool] = is_transient,
               cancel_check: Callable[[], None] | None = None,
               rng: random.Random | None = None,
               sleep: Callable[[float], None] = time.sleep,
               label: str = "") -> Any:
    """Call ``fn`` until it succeeds, a non-retryable exception escapes, or
    the policy's attempt/time budget runs out (the last exception re-raises).

    ``cancel_check`` runs between backoff quanta (and before each retry);
    anything it raises — JobPaused, JobCanceled — propagates immediately,
    abandoning the backoff. The pending transient exception is dropped: by
    definition retrying it could have succeeded, and the checkpoint the
    pause serializes reflects only committed work either way.
    """
    rng = rng or random
    deadline = time.monotonic() + policy.budget_s
    retries = 0
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — classified below
            if not classify(exc):
                raise
            retries += 1
            if retries >= policy.attempts:
                _GAVE_UP.inc()
                raise
            delay = policy.delay(retries - 1, rng)
            now = time.monotonic()
            if now + delay > deadline:
                _GAVE_UP.inc()
                raise
            logger.debug("retry %d/%d%s in %.3fs after %r",
                         retries, policy.attempts - 1,
                         f" [{label}]" if label else "", delay, exc)
            waited = 0.0
            while waited < delay:
                if cancel_check is not None:
                    cancel_check()
                quantum = min(POLL_S, delay - waited)
                sleep(quantum)
                waited += quantum
            if cancel_check is not None:
                cancel_check()
            _ATTEMPTS.inc()
            _BACKOFF_S.inc(waited)
