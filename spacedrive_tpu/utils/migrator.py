"""Versioned-JSON config migration framework.

Equivalent of the reference's generic migrator (core/src/util/migrator.rs:15-40,
``load_and_migrate`` :41+): configs are stored as JSON with a ``version`` field;
loading a file at an older version runs each registered migration step in order,
persisting after every step so a crash mid-upgrade resumes cleanly.

Usage::

    class NodeConfig(VersionedConfig):
        VERSION = 2
        FILENAME = "node_state.sdconfig"

        @migration(1, 2)
        def _one_to_two(data: dict) -> dict: ...
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, ClassVar


class MigratorError(Exception):
    pass


def migration(from_version: int, to_version: int):
    """Decorator registering a pure dict→dict migration step."""
    if to_version != from_version + 1:
        raise MigratorError(f"migrations must be sequential: {from_version}→{to_version}")

    def wrap(fn: Callable[[dict], dict]):
        fn.__migration__ = (from_version, to_version)
        return staticmethod(fn)

    return wrap


class VersionedConfig:
    """Base for JSON configs with sequential versioned migrations.

    Subclasses define ``VERSION`` (current), field defaults via ``defaults()``,
    and migration steps with the ``@migration`` decorator. The on-disk form is
    ``{"version": N, ...fields}`` (the reference flattens the same way,
    migrator.rs ``BaseConfig{version, flattened}``).
    """

    VERSION: ClassVar[int] = 1

    def __init__(self, path: str | Path, data: dict[str, Any]) -> None:
        self.path = Path(path)
        self.data = data

    # -- subclass surface ---------------------------------------------------
    @classmethod
    def defaults(cls) -> dict[str, Any]:
        return {}

    # -- persistence --------------------------------------------------------
    @classmethod
    def _migrations(cls) -> dict[int, Callable[[dict], dict]]:
        steps: dict[int, Callable[[dict], dict]] = {}
        for name in dir(cls):
            fn = getattr(cls, name)
            meta = getattr(fn, "__migration__", None)
            if meta is not None:
                steps[meta[0]] = fn
        return steps

    @classmethod
    def load_and_migrate(cls, path: str | Path) -> "VersionedConfig":
        path = Path(path)
        if not path.exists():
            cfg = cls(path, {"version": cls.VERSION, **cls.defaults()})
            cfg.save()
            return cfg

        data = json.loads(path.read_text())
        version = data.get("version")
        if version is None:
            raise MigratorError(f"{path}: missing version field")
        if version > cls.VERSION:
            raise MigratorError(
                f"{path}: version {version} is newer than supported {cls.VERSION}"
            )
        steps = cls._migrations()
        cfg = cls(path, data)
        while version < cls.VERSION:
            step = steps.get(version)
            if step is None:
                raise MigratorError(f"{path}: no migration from version {version}")
            cfg.data = step(cfg.data)
            version += 1
            cfg.data["version"] = version
            cfg.save()  # persist each step, like load_and_migrate does
        # backfill any new defaults without clobbering existing values; persist
        # so generated defaults (ids, keypair seeds) are stable across boots
        backfilled = False
        for key, value in cls.defaults().items():
            if key not in cfg.data:
                cfg.data[key] = value
                backfilled = True
        if backfilled:
            cfg.save()
        return cfg

    def save(self) -> None:
        # full tempfile→fsync→rename discipline (utils/atomic): config
        # sidecars are the one artifact whose torn write can make a whole
        # library unloadable, and the old tmp+rename skipped the fsync —
        # a power cut could rename an empty tmp into place
        from .atomic import atomic_write_text

        try:
            atomic_write_text(self.path, json.dumps(self.data, indent=2,
                                                    sort_keys=True))
        except OSError as e:
            from ..recovery import is_disk_full, note_disk_full

            if is_disk_full(e):
                note_disk_full("config")
            raise

    # -- dict-ish access ----------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self.data[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self.data.get(key, default)

    def __setitem__(self, key: str, value: Any) -> None:
        self.data[key] = value
