"""Volume enumeration: mounted disks with capacity + SSD/HDD classification.

Parity with core/src/volume/mod.rs (sysinfo-based: get_volumes :66/:206, SSD
classification :168) — implemented Linux-native for the TPU host: parse
/proc/mounts, statvfs for capacity, and /sys/block/<dev>/queue/rotational for
disk kind. Pseudo filesystems are skipped the way the reference filters
overlay/snap mounts.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

_PSEUDO_FS = {
    "proc", "sysfs", "devtmpfs", "devpts", "tmpfs", "cgroup", "cgroup2",
    "securityfs", "pstore", "efivarfs", "bpf", "debugfs", "tracefs",
    "fusectl", "configfs", "ramfs", "autofs", "mqueue", "hugetlbfs",
    "binfmt_misc", "overlay", "squashfs", "nsfs", "rpc_pipefs", "fuse.lxcfs",
}


def _disk_kind(device: str) -> str:
    """SSD | HDD | Unknown via the block queue rotational flag."""
    name = os.path.basename(device)
    # strip partition suffixes: sda1 -> sda, nvme0n1p2 -> nvme0n1
    for candidate in (name, name.rstrip("0123456789"),
                      name.split("p")[0] if "p" in name else name):
        rot = Path(f"/sys/block/{candidate}/queue/rotational")
        if rot.exists():
            try:
                return "HDD" if rot.read_text().strip() == "1" else "SSD"
            except OSError:
                return "Unknown"
    return "Unknown"


def get_volumes() -> list[dict[str, Any]]:
    volumes: list[dict[str, Any]] = []
    seen_mounts: set[str] = set()
    try:
        with open("/proc/mounts") as fh:
            lines = fh.readlines()
    except OSError:
        lines = []
    for line in lines:
        parts = line.split()
        if len(parts) < 3:
            continue
        device, mount_point, fs_type = parts[0], parts[1], parts[2]
        if fs_type in _PSEUDO_FS or not device.startswith("/"):
            continue
        mount_point = mount_point.replace("\\040", " ")
        if mount_point in seen_mounts:
            continue
        seen_mounts.add(mount_point)
        try:
            st = os.statvfs(mount_point)
        except OSError:
            continue
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        volumes.append({
            "name": os.path.basename(mount_point) or mount_point,
            "mount_point": mount_point,
            "file_system": fs_type,
            "total_capacity": total,
            "available_capacity": free,
            "disk_type": _disk_kind(device),
            "is_root_filesystem": mount_point == "/",
        })
    if not volumes:  # container without /proc/mounts visibility: report cwd fs
        st = os.statvfs("/")
        volumes.append({
            "name": "/", "mount_point": "/", "file_system": "unknown",
            "total_capacity": st.f_blocks * st.f_frsize,
            "available_capacity": st.f_bavail * st.f_frsize,
            "disk_type": "Unknown", "is_root_filesystem": True,
        })
    return volumes


def volume_for_path(path: str | Path) -> dict[str, Any] | None:
    """Longest-prefix mount match (used by library statistics)."""
    path = str(Path(path).resolve())
    best = None
    for vol in get_volumes():
        mp = vol["mount_point"]
        if path == mp or path.startswith(mp.rstrip("/") + "/") or mp == "/":
            if best is None or len(mp) > len(best["mount_point"]):
                best = vol
    return best
