"""Desktop shell: host the core + web explorer as a local app.

The reference's desktop app is a Tauri webview over the same core its
server shell exposes (apps/desktop/src-tauri/src/main.rs:74-180: rspc
transport + a localhost axum server for custom_uri + window plumbing).
On a Linux/TPU host there is no bundled webview toolkit, so this shell is
the same composition with the system browser as the window: boot the
node, serve the API + web explorer on localhost only, open the UI, and
shut the core down cleanly when asked.

What it keeps from the Tauri shell's responsibilities:
- single-instance guard (second launch focuses the first: here it prints
  the running instance's URL instead of double-booting the core)
- localhost-only binding; pass ``--auth user:password`` to additionally
  require credentials on multi-user hosts (any local user can reach a
  localhost port — an unauthenticated API there exposes e.g.
  keys.getKey to other accounts)
- app_ready / reset_spacedrive / open_logs_dir equivalents as commands
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import threading
import time
from pathlib import Path

DEFAULT_DATA_DIR = "~/.local/share/spacedrive_tpu"


def _instance_file(data_dir: Path) -> Path:
    return data_dir / "desktop_instance.json"


def _proc_start_time(pid: int) -> int | None:
    """Kernel start time (clock ticks since boot, /proc/<pid>/stat field
    22) — constant for a process's whole life and different for any
    process that later recycles the pid, which makes (pid, starttime) a
    unique process identity cmdline substrings can never be."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm (field 2) may contain spaces/parens; fields resume after
        # the LAST ')'
        fields = stat.rsplit(")", 1)[1].split()
        return int(fields[19])  # starttime is field 22 (1-based)
    except (OSError, IndexError, ValueError):
        return None


def _proc_argv(pid: int) -> list[str] | None:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            raw = f.read()
        return raw.decode("utf-8", "replace").split("\0")[:-1] or None
    except OSError:
        return None


def _instance_alive(info: dict) -> bool:
    """A recycled pid can impersonate a dead shell, so pid liveness alone
    is not trusted: the recorded URL must also answer /health. An entry
    still booting (url not yet recorded) counts as alive while its pid —
    verified by start time — is.

    A live node mid-scan on a loaded single-core host can miss a short
    health deadline, and declaring it dead would let a concurrent launch
    unlink its claim and boot a second Node over the same data dir — the
    exact hazard single-instancing exists to prevent. So the probe is
    generous (10s) and retried once, and an unresponsive-but-live pid is
    only kept when its /proc start time (recorded at claim time) proves
    it is the same process that claimed — a substring match on a
    recycled pid's cmdline proves nothing and is gone."""
    try:
        pid = int(info["pid"])
        os.kill(pid, 0)
    except (OSError, ValueError, KeyError, TypeError):
        return False

    def same_process() -> bool:
        recorded = info.get("starttime")
        if recorded is not None:
            actual = _proc_start_time(pid)
            if actual is None:
                # /proc answered at claim time but not now: cannot
                # DISPROVE identity — err alive (a blocked launch beats
                # booting a second Node over the same data dir)
                return True
            return int(recorded) == actual
        argv = info.get("argv")  # claim written where /proc had no stat
        if argv:
            actual_argv = _proc_argv(pid)
            if actual_argv is None:
                return True  # no /proc on this host: err alive
            return actual_argv == argv
        # nothing recorded that can prove identity: a live pid with a
        # dead/absent URL is indistinguishable from a recycled pid —
        # treat the claim as stale (the health probe already failed)
        return False

    url = info.get("url")
    if url is None:
        return same_process()  # claimed, server still starting
    import urllib.request

    for attempt in range(2):
        if attempt:
            time.sleep(1.0)
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/health",
                                        timeout=10) as resp:
                if resp.status == 200:
                    return True
        except Exception:
            pass
    # Unresponsive but the pid is alive: busy shell vs recycled pid,
    # decided by process identity, not cmdline substrings.
    return same_process()


def _instance_lock(data_dir: Path):
    """flock-guarded critical section for every read-check-mutate of the
    instance file — serializing launchers is the only way a stale-file
    cleanup can't delete a competitor's fresh claim (plain unlink is a
    TOCTOU)."""
    import contextlib
    import fcntl

    @contextlib.contextmanager
    def guard():
        data_dir.mkdir(parents=True, exist_ok=True)
        with open(data_dir / "desktop_instance.lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)

    return guard()


def _running_instance(data_dir: Path) -> dict | None:
    """The live instance's {pid, url}, or None. Stale files (dead pid or a
    URL that no longer answers) are cleaned up rather than blocking a
    relaunch."""
    with _instance_lock(data_dir):
        return _running_instance_locked(data_dir)


def _running_instance_locked(data_dir: Path) -> dict | None:
    f = _instance_file(data_dir)
    try:
        info = json.loads(f.read_text())
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        info = None
    if info is not None and _instance_alive(info):
        return info
    try:
        f.unlink()
    except OSError:
        pass
    return None


def _claim_payload(url: str | None) -> dict:
    """The instance record: pid plus the identity proof (/proc start time,
    argv fallback) that lets a later launcher tell THIS process apart
    from whatever recycles its pid after a crash."""
    pid = os.getpid()
    return {"pid": pid, "url": url,
            "starttime": _proc_start_time(pid),
            "argv": _proc_argv(pid) or sys.argv}


def _claim_instance(data_dir: Path) -> bool:
    """Atomically claim the single-instance slot. Returns False when a live
    instance (or one mid-boot) holds the claim."""
    with _instance_lock(data_dir):
        if _running_instance_locked(data_dir) is not None:
            return False
        fd = os.open(str(_instance_file(data_dir)),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
        with os.fdopen(fd, "w") as fh:
            json.dump(_claim_payload(None), fh)
        return True


def launch(data_dir: str | Path, port: int = 0, open_browser: bool = True,
           wait: bool = True, auth: str | None = None) -> dict:
    """Boot node + server, register the instance, optionally open the UI.
    Returns {url, node, shell}; with wait=True blocks until SIGINT/SIGTERM
    and shuts down before returning. ``auth``: "user:password" to require
    basic auth on every route (recommended on multi-user hosts)."""
    from .node import Node
    from .server.shell import Server

    data_dir = Path(os.path.expanduser(str(data_dir)))
    data_dir.mkdir(parents=True, exist_ok=True)
    if not _claim_instance(data_dir):
        existing = _running_instance(data_dir) or {}
        print(f"already running (pid {existing.get('pid')}): "
              f"{existing.get('url') or '(starting)'}")
        return {"url": existing.get("url"), "node": None, "shell": None}

    try:
        node = Node(data_dir)
        shell = Server(node, host="127.0.0.1", port=port, auth=auth)
        shell.start()
    except BaseException:
        try:
            _instance_file(data_dir).unlink()
        except OSError:
            pass
        raise
    url = f"http://127.0.0.1:{shell.port}/"
    _instance_file(data_dir).write_text(json.dumps(_claim_payload(url)))

    if open_browser:
        import webbrowser

        threading.Thread(target=webbrowser.open, args=(url,),
                         daemon=True).start()
    print(f"spacedrive_tpu desktop at {url} (data: {data_dir})")

    if not wait:
        return {"url": url, "node": node, "shell": shell}

    stop = threading.Event()

    def _on_signal(_sig, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    try:
        stop.wait()
    finally:
        shutdown(data_dir, node, shell)
    return {"url": url, "node": None, "shell": None}


def shutdown(data_dir: Path, node, shell) -> None:
    try:
        shell.stop()
    finally:
        node.shutdown()
        try:
            _instance_file(data_dir).unlink()
        except OSError:
            pass


def reset(data_dir: str | Path) -> None:
    """reset_spacedrive (tauri_plugins command): wipe the data dir after the
    instance is confirmed not running."""
    data_dir = Path(os.path.expanduser(str(data_dir)))
    if _running_instance(data_dir) is not None:
        raise RuntimeError("instance is running; stop it before resetting")
    if data_dir.exists():
        shutil.rmtree(data_dir)
        print(f"removed {data_dir}")


def logs_dir(data_dir: str | Path) -> Path:
    """open_logs_dir equivalent: resolve (and print) the log directory."""
    d = Path(os.path.expanduser(str(data_dir))) / "logs"
    print(d)
    return d


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spacedrive_tpu.desktop",
        description="Local desktop app: core + web explorer in the browser")
    parser.add_argument("--data-dir", default=DEFAULT_DATA_DIR)
    parser.add_argument("--port", type=int, default=0,
                        help="0 picks a free port")
    parser.add_argument("--no-open", action="store_true",
                        help="don't open the browser (headless/session use)")
    parser.add_argument("--auth", default=None, metavar="USER:PASSWORD",
                        help="require basic auth (recommended on multi-user "
                             "hosts; prefer SD_DESKTOP_AUTH — argv is "
                             "readable by other local users via /proc)")
    parser.add_argument("command", nargs="?", default="run",
                        choices=["run", "reset", "logs"])
    args = parser.parse_args(argv)

    if args.command == "reset":
        reset(args.data_dir)
        return 0
    if args.command == "logs":
        logs_dir(args.data_dir)
        return 0
    # env var wins: a credential on the command line is visible to every
    # local user via /proc/<pid>/cmdline — the very host type that needs it
    auth = os.environ.get("SD_DESKTOP_AUTH") or args.auth
    launch(args.data_dir, port=args.port, open_browser=not args.no_open,
           auth=auth)
    return 0


if __name__ == "__main__":
    sys.exit(main())
