"""The columnar search index: fixed-width byte planes + filter columns.

One :class:`ColumnarIndex` per library holds every ``file_path`` row as a
fixed-width columnar record (ISSUE 15 tentpole):

- **byte planes** (``(W, N) u8``, plane ``w`` = byte ``w`` of every row,
  the lane layout ops/blake3_pallas.py set the precedent for): folded
  ``name`` (W=64) for LIKE-substring scoring, raw ``materialized_path``
  (W=96) and ``extension`` (W=12) for SQL ``=``/``IN`` byte equality,
  and ``date_created`` (W=40) for BINARY-collation range compares;
- **filter columns**: ``location_id``/``kind`` (i64/i32, −1 = NULL),
  ``hidden``/``favorite`` (i8, −1 = NULL), ``size_in_bytes`` (i64, −1 =
  NULL) — the date/kind/size/hidden predicate set;
- a byte-presence bitmap (``(N, 32) u8``) — the CPU engine's substring
  prescreen (kernels.presence_bitmap);
- an **overflow sidecar**: the few rows whose value truncated at a plane
  width (or whose date text is longer than W_DATE) keep their full
  decoded fields host-side; every query patches those rows through
  :func:`match_row`, the pure-Python oracle, so truncation can never
  change an answer.

Rows are kept sorted by ``id`` (AUTOINCREMENT ids are monotonic, so
appends preserve the invariant and slot lookup is a binary search);
deletes flip an ``alive`` bit; updates are written in place. The
:class:`DeviceMirror` keeps jnp copies of the planes + filter columns
resident on the accelerator, updated by the same incremental deltas —
the "device-resident" half of the engine's name.

Semantics are the SQL path's, exactly (the engine's byte-identity
contract): :func:`parse_predicate` normalizes a ``search.paths`` arg
with the SAME coercions api/routers/search.py applies, and returns None
for anything the index cannot answer bit-exactly (LIKE wildcards in the
needle, tag subqueries, NUL bytes, over-long needles) — those queries
stay on SQLite.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

import numpy as np

from . import kernels
from .kernels import MAX_NEEDLE, fold

W_NAME = 64
W_PATH = 96
W_EXT = 12
W_DATE = 40

#: sentinel for NULL in integer filter columns (no real value collides:
#: ids/sizes/kinds/locations are non-negative, hidden/favorite are 0/1)
NULL_I = -1

_GROW = 4096  # minimum capacity step (one Pallas tile of rows)


@dataclasses.dataclass(frozen=True)
class Predicate:
    """A normalized, index-answerable ``search.paths`` filter set."""

    location: int | None = None
    needle: bytes | None = None          # folded LIKE-substring needle
    exts: tuple[bytes, ...] | None = None
    kinds: tuple[int, ...] | None = None
    favorite: int | None = None
    exclude_hidden: bool = False
    path: bytes | None = None            # materialized_path equality
    date_lo: bytes | None = None
    date_hi: bytes | None = None
    size_lo: int | None = None
    size_hi: int | None = None


def parse_predicate(arg: dict[str, Any]) -> tuple[Predicate | None, str]:
    """(predicate, "") when the index can answer this filter set
    bit-exactly, else (None, reason). Coercions mirror
    api/routers/search.py `_path_filters` EXACTLY — any divergence is a
    byte-identity bug, so prefer returning None over approximating."""
    if arg.get("tags"):
        return None, "tags"  # subquery over tag_on_object — SQLite's
    pred: dict[str, Any] = {}
    if arg.get("location_id") is not None:
        v = arg["location_id"]
        if not isinstance(v, int) or isinstance(v, bool):
            return None, "arg"
        pred["location"] = v
    if arg.get("search"):
        # the SQL path binds f"%{search}%": stringified, % and _ live as
        # LIKE wildcards there — wildcard semantics stay on SQLite
        needle = fold(str(arg["search"]).encode("utf-8"))
        if (b"%" in needle or b"_" in needle or b"\x00" in needle
                or not 1 <= len(needle) <= MAX_NEEDLE):
            return None, "needle"
        pred["needle"] = needle
    if arg.get("extensions"):
        try:
            exts = tuple(e.lstrip(".").lower().encode("utf-8")
                         for e in arg["extensions"])
        except AttributeError:
            return None, "arg"
        if any(b"\x00" in e for e in exts):
            return None, "arg"
        pred["exts"] = exts
    if arg.get("kinds"):
        kinds = tuple(arg["kinds"])
        if not all(isinstance(k, int) and not isinstance(k, bool)
                   for k in kinds):
            return None, "arg"
        pred["kinds"] = kinds
    if arg.get("favorite") is not None:
        try:
            pred["favorite"] = int(arg["favorite"])
        except (TypeError, ValueError):
            return None, "arg"
    if not arg.get("include_hidden"):
        pred["exclude_hidden"] = True
    if arg.get("materialized_path"):
        v = arg["materialized_path"]
        if not isinstance(v, str):
            return None, "arg"
        pred["path"] = v.encode("utf-8")
    if arg.get("date_range"):
        rng = arg["date_range"]
        if not isinstance(rng, (list, tuple)) or len(rng) != 2:
            return None, "arg"
        for key, bound in zip(("date_lo", "date_hi"), rng):
            if bound is None:
                continue
            if not isinstance(bound, str):
                return None, "arg"
            raw = bound.encode("utf-8")
            if len(raw) > W_DATE or b"\x00" in raw:
                return None, "arg"
            pred[key] = raw
    if arg.get("size_range"):
        rng = arg["size_range"]
        if not isinstance(rng, (list, tuple)) or len(rng) != 2:
            return None, "arg"
        for key, bound in zip(("size_lo", "size_hi"), rng):
            if bound is None:
                continue
            if not isinstance(bound, int) or isinstance(bound, bool):
                return None, "arg"
            pred[key] = bound
    return Predicate(**pred), ""


def match_row(fields: dict[str, Any], pred: Predicate) -> bool:
    """Pure-Python row matcher with the SQL path's exact semantics — the
    overflow-row patch and the parity oracle tests compare every engine
    against."""
    if pred.location is not None and fields.get("location_id") != pred.location:
        return False
    if pred.exclude_hidden:
        hidden = fields.get("hidden")
        if not (hidden is None or not hidden):
            return False
    if pred.needle is not None:
        name = fields.get("name")
        if name is None or pred.needle not in fold(name.encode("utf-8")):
            return False
    if pred.exts is not None:
        ext = fields.get("extension")
        if ext is None or ext.encode("utf-8") not in pred.exts:
            return False
    if pred.path is not None:
        path = fields.get("materialized_path")
        if path is None or path.encode("utf-8") != pred.path:
            return False
    if pred.kinds is not None:
        kind = fields.get("kind")
        if kind is None or kind not in pred.kinds:
            return False
    if pred.favorite is not None:
        fav = fields.get("favorite")
        if fav is None or int(fav) != pred.favorite:
            return False
    if pred.date_lo is not None or pred.date_hi is not None:
        date = fields.get("date_created")
        if date is None:
            return False
        raw = str(date).encode("utf-8")
        if pred.date_lo is not None and raw < pred.date_lo:
            return False
        if pred.date_hi is not None and raw > pred.date_hi:
            return False
    if pred.size_lo is not None or pred.size_hi is not None:
        size = fields.get("size_in_bytes")
        if size is None:
            return False
        if pred.size_lo is not None and size < pred.size_lo:
            return False
        if pred.size_hi is not None and size > pred.size_hi:
            return False
    return True


#: the loader SELECT every build/refresh path uses (LEFT JOIN pulls the
#: object-side filter columns; decode stays cheap — raw sqlite3.Row)
LOADER_SQL = (
    "SELECT fp.id AS id, fp.name AS name, fp.extension AS extension, "
    "fp.materialized_path AS materialized_path, "
    "fp.location_id AS location_id, fp.hidden AS hidden, "
    "fp.size_in_bytes AS size_in_bytes, fp.date_created AS date_created, "
    "o.kind AS kind, o.favorite AS favorite "
    "FROM file_path fp LEFT JOIN object o ON fp.object_id = o.id")


def _text_bytes(value: Any) -> bytes | None:
    if value is None:
        return None
    return str(value).encode("utf-8")


class ColumnarIndex:
    """The numpy master copy (the CPU engine reads it directly)."""

    def __init__(self) -> None:
        self.n = 0
        self.cap = 0
        self.ids = np.empty(0, dtype=np.int64)
        self.alive = np.empty(0, dtype=bool)
        self.name_planes = np.empty((W_NAME, 0), dtype=np.uint8)
        self.name_len = np.empty(0, dtype=np.int32)
        self.path_planes = np.empty((W_PATH, 0), dtype=np.uint8)
        self.path_len = np.empty(0, dtype=np.int32)
        self.ext_planes = np.empty((W_EXT, 0), dtype=np.uint8)
        self.ext_len = np.empty(0, dtype=np.int32)
        self.date_planes = np.empty((W_DATE, 0), dtype=np.uint8)
        self.date_len = np.empty(0, dtype=np.int32)
        self.location = np.empty(0, dtype=np.int64)
        self.hidden = np.empty(0, dtype=np.int8)
        self.kind = np.empty(0, dtype=np.int32)
        self.favorite = np.empty(0, dtype=np.int8)
        self.size = np.empty(0, dtype=np.int64)
        self.bits = np.empty((0, 32), dtype=np.uint8)
        #: id -> full decoded fields for rows a fixed width truncated
        self.overflow: dict[int, dict[str, Any]] = {}
        #: monotonically bumped on every mutation — the DeviceMirror
        #: resyncs (incrementally) when its generation falls behind
        self.generation = 0
        self._delta_slots: list[int] = []

    # -- capacity ------------------------------------------------------------
    def _ensure_cap(self, extra: int) -> None:
        need = self.n + extra
        if need <= self.cap:
            return
        new_cap = max(_GROW, self.cap * 2)
        while new_cap < need:
            new_cap *= 2

        def grow1(arr, fill=0):
            out = np.full(new_cap, fill, dtype=arr.dtype)
            out[: self.n] = arr[: self.n]
            return out

        def grow2(arr):
            out = np.zeros((arr.shape[0], new_cap), dtype=np.uint8)
            out[:, : self.n] = arr[:, : self.n]
            return out

        self.ids = grow1(self.ids)
        self.alive = grow1(self.alive, fill=False)
        self.name_planes = grow2(self.name_planes)
        self.name_len = grow1(self.name_len)
        self.path_planes = grow2(self.path_planes)
        self.path_len = grow1(self.path_len)
        self.ext_planes = grow2(self.ext_planes)
        self.ext_len = grow1(self.ext_len)
        self.date_planes = grow2(self.date_planes)
        self.date_len = grow1(self.date_len)
        self.location = grow1(self.location)
        self.hidden = grow1(self.hidden)
        self.kind = grow1(self.kind)
        self.favorite = grow1(self.favorite)
        self.size = grow1(self.size)
        bits = np.zeros((new_cap, 32), dtype=np.uint8)
        bits[: self.n] = self.bits[: self.n]
        self.bits = bits
        self.cap = new_cap
        #: capacity change invalidates every mirror slice — full resync
        self._delta_slots = None  # type: ignore[assignment]

    # -- row encode ----------------------------------------------------------
    def _write_plane(self, planes: np.ndarray, lens: np.ndarray,
                     slot: int, raw: bytes | None) -> bool:
        """Returns True when the value overflowed its plane width."""
        width = planes.shape[0]
        planes[:, slot] = 0
        if raw is None:
            lens[slot] = NULL_I
            return False
        clipped = raw[:width]
        if clipped:
            planes[: len(clipped), slot] = np.frombuffer(
                clipped, dtype=np.uint8)
        lens[slot] = len(raw)
        return len(raw) > width

    def _write_row(self, slot: int, row: Any, bitmap: bool = True) -> None:
        fields = {k: row[k] for k in row.keys()} if not isinstance(row, dict) \
            else row
        self.ids[slot] = fields["id"]
        self.alive[slot] = True
        name_raw = _text_bytes(fields.get("name"))
        over = self._write_plane(self.name_planes, self.name_len, slot,
                                 None if name_raw is None
                                 else fold(name_raw))
        over |= self._write_plane(self.path_planes, self.path_len, slot,
                                  _text_bytes(fields.get("materialized_path")))
        over |= self._write_plane(self.ext_planes, self.ext_len, slot,
                                  _text_bytes(fields.get("extension")))
        over |= self._write_plane(self.date_planes, self.date_len, slot,
                                  _text_bytes(fields.get("date_created")))
        loc = fields.get("location_id")
        self.location[slot] = NULL_I if loc is None else loc
        hidden = fields.get("hidden")
        self.hidden[slot] = NULL_I if hidden is None else int(bool(hidden))
        kind = fields.get("kind")
        self.kind[slot] = NULL_I if kind is None else kind
        fav = fields.get("favorite")
        self.favorite[slot] = NULL_I if fav is None else int(bool(fav))
        size = fields.get("size_in_bytes")
        self.size[slot] = NULL_I if size is None else size
        row_id = int(fields["id"])
        if over:
            self.overflow[row_id] = {
                "name": fields.get("name"),
                "extension": fields.get("extension"),
                "materialized_path": fields.get("materialized_path"),
                "date_created": fields.get("date_created"),
                "location_id": loc, "hidden": hidden, "kind": kind,
                "favorite": fav, "size_in_bytes": size,
            }
        else:
            self.overflow.pop(row_id, None)
        if bitmap:
            # per-row presence bitmap for incremental updates; bulk build
            # overwrites with the vectorized pass instead
            self.bits[slot] = kernels.presence_bitmap(
                self.name_planes[:, slot: slot + 1],
                self.name_len[slot: slot + 1])[0]

    def _note_delta(self, slot: int) -> None:
        self.generation += 1
        if self._delta_slots is not None:
            self._delta_slots.append(slot)
            if len(self._delta_slots) > 4096:
                self._delta_slots = None  # type: ignore[assignment]

    # -- bulk build ----------------------------------------------------------
    def build(self, rows: Iterable[Any]) -> None:
        rows = list(rows)
        self.n = 0
        self.cap = 0
        self.overflow.clear()
        self.ids = np.empty(0, dtype=np.int64)  # force regrow
        self._ensure_cap(max(len(rows), 1))
        for i, row in enumerate(rows):
            self._write_row(i, row, bitmap=False)
        self.n = len(rows)
        # bulk bitmap (the per-row writes above already set it, but the
        # vectorized pass is ~10x faster at build scale — overwrite)
        if self.n:
            self.bits[: self.n] = kernels.presence_bitmap(
                self.name_planes[:, : self.n], self.name_len[: self.n])
        self.generation += 1
        self._delta_slots = None  # type: ignore[assignment]

    # -- incremental ---------------------------------------------------------
    def slot_of(self, row_id: int) -> int | None:
        i = int(np.searchsorted(self.ids[: self.n], row_id))
        if i < self.n and self.ids[i] == row_id:
            return i
        return None

    @property
    def max_id(self) -> int:
        return int(self.ids[self.n - 1]) if self.n else 0

    @property
    def alive_count(self) -> int:
        return int(self.alive[: self.n].sum())

    def upsert(self, row: Any) -> bool:
        """Update in place or append; False = the row's id is below
        ``max_id`` but unknown (an explicit-id insert the sorted-append
        invariant cannot absorb — the caller full-rebuilds)."""
        row_id = int(row["id"])
        slot = self.slot_of(row_id)
        if slot is None:
            if row_id <= self.max_id:
                return False
            self._ensure_cap(1)
            slot = self.n
            self.n += 1
        self._write_row(slot, row)
        self._note_delta(slot)
        return True

    def delete_id(self, row_id: int) -> None:
        slot = self.slot_of(row_id)
        if slot is not None and self.alive[slot]:
            self.alive[slot] = False
            self.overflow.pop(row_id, None)
            self._note_delta(slot)

    # -- introspection -------------------------------------------------------
    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in (
            self.ids, self.alive, self.name_planes, self.name_len,
            self.path_planes, self.path_len, self.ext_planes, self.ext_len,
            self.date_planes, self.date_len, self.location, self.hidden,
            self.kind, self.favorite, self.size, self.bits))

    def consume_delta(self) -> list[int] | None:
        """Changed slots since the last call (None = resync everything);
        the DeviceMirror's incremental-update feed."""
        delta = self._delta_slots
        self._delta_slots = []
        return delta


class DeviceMirror:
    """jnp copies of the scorable columns, resident on the accelerator
    and patched incrementally (``.at[].set`` scatters) from the master's
    delta feed — queries never pay a host→device transfer of the index."""

    def __init__(self) -> None:
        self.generation = -1
        self.cap = 0
        self.arrays: dict[str, Any] = {}

    def sync(self, idx: ColumnarIndex) -> None:
        import jax.numpy as jnp

        if self.generation == idx.generation and self.cap:
            idx.consume_delta()  # stay drained
            return
        delta = idx.consume_delta()
        dev_cap = kernels.pad_cap(max(idx.n, 1))
        if delta is None or dev_cap != self.cap or not self.arrays:
            self.cap = dev_cap

            def pad2(planes):
                out = np.zeros((planes.shape[0], dev_cap), dtype=np.uint8)
                out[:, : idx.n] = planes[:, : idx.n]
                return jnp.asarray(out)

            def pad1(arr, fill):
                out = np.full(dev_cap, fill, dtype=arr.dtype)
                out[: idx.n] = arr[: idx.n]
                return jnp.asarray(out)

            self.arrays = {
                "name": pad2(idx.name_planes),
                "path": pad2(idx.path_planes),
                "ext": pad2(idx.ext_planes),
                "date": pad2(idx.date_planes),
                "name_len": pad1(idx.name_len, NULL_I),
                "path_len": pad1(idx.path_len, NULL_I),
                "ext_len": pad1(idx.ext_len, NULL_I),
                "date_len": pad1(idx.date_len, NULL_I),
                "location": pad1(idx.location, NULL_I),
                "hidden": pad1(idx.hidden, NULL_I),
                "kind": pad1(idx.kind, NULL_I),
                "favorite": pad1(idx.favorite, NULL_I),
                "size": pad1(idx.size, NULL_I),
                "alive": pad1(idx.alive, False),
            }
        elif delta:
            slots = np.unique(np.asarray(delta, dtype=np.int64))
            for key, planes, lens in (
                    ("name", idx.name_planes, idx.name_len),
                    ("path", idx.path_planes, idx.path_len),
                    ("ext", idx.ext_planes, idx.ext_len),
                    ("date", idx.date_planes, idx.date_len)):
                self.arrays[key] = self.arrays[key].at[:, slots].set(
                    jnp.asarray(planes[:, slots]))
                self.arrays[f"{key}_len"] = \
                    self.arrays[f"{key}_len"].at[slots].set(
                        jnp.asarray(lens[slots]))
            for key, col in (("location", idx.location),
                             ("hidden", idx.hidden), ("kind", idx.kind),
                             ("favorite", idx.favorite), ("size", idx.size),
                             ("alive", idx.alive)):
                self.arrays[key] = self.arrays[key].at[slots].set(
                    jnp.asarray(col[slots]))
        self.generation = idx.generation


# ---------------------------------------------------------------------------
# mask evaluation — one numpy engine, one device engine, same answers
# ---------------------------------------------------------------------------


def eval_mask_cpu(idx: ColumnarIndex, pred: Predicate) -> np.ndarray:
    """(n,) bool over the master arrays (prescreened exact matching)."""
    n = idx.n
    m = idx.alive[:n].copy()
    # negative filter values would collide with the NULL sentinel (−1):
    # SQL `col = -1` matches nothing (no stored negatives), so mirror that
    if pred.location is not None:
        m &= (idx.location[:n] == pred.location) if pred.location >= 0 \
            else np.zeros(n, dtype=bool)
    if pred.exclude_hidden:
        m &= idx.hidden[:n] <= 0
    if pred.kinds is not None:
        kinds = [k for k in pred.kinds if k >= 0]
        m &= np.isin(idx.kind[:n], np.asarray(kinds, dtype=np.int64)) \
            if kinds else np.zeros(n, dtype=bool)
    if pred.favorite is not None:
        m &= (idx.favorite[:n] == pred.favorite) if pred.favorite >= 0 \
            else np.zeros(n, dtype=bool)
    if pred.size_lo is not None:
        m &= (idx.size[:n] >= 0) & (idx.size[:n] >= pred.size_lo)
    if pred.size_hi is not None:
        m &= (idx.size[:n] >= 0) & (idx.size[:n] <= pred.size_hi)
    if pred.exts is not None:
        ext_m = np.zeros(n, dtype=bool)
        for needle in pred.exts:
            ext_m |= (kernels.exact_np(idx.ext_planes[:, :n], needle)
                      & (idx.ext_len[:n] == len(needle)))
        m &= ext_m
    if pred.path is not None:
        m &= (kernels.exact_np(idx.path_planes[:, :n], pred.path)
              & (idx.path_len[:n] == len(pred.path)))
    if pred.date_lo is not None or pred.date_hi is not None:
        valid = idx.date_len[:n] >= 0
        if pred.date_lo is not None:
            m &= valid & (kernels.lex_cmp_np(idx.date_planes[:, :n],
                                             pred.date_lo) >= 0)
        if pred.date_hi is not None:
            m &= valid & (kernels.lex_cmp_np(idx.date_planes[:, :n],
                                             pred.date_hi) <= 0)
    if pred.needle is not None:
        cand = m & kernels.prescreen_np(idx.bits[:n], pred.needle)
        sub_idx = np.flatnonzero(cand)
        sub_m = np.zeros(n, dtype=bool)
        if sub_idx.size:
            sub = np.ascontiguousarray(idx.name_planes[:, sub_idx])
            sub_m[sub_idx] = kernels.substring_np(sub, pred.needle)
        m &= sub_m
    _patch_overflow(idx, pred, m)
    return m


def eval_mask_device(idx: ColumnarIndex, mirror: DeviceMirror,
                     pred: Predicate, kernel: str) -> np.ndarray:
    """(n,) bool via the resident jnp arrays + the selected kernel —
    byte-identical to :func:`eval_mask_cpu` (tests/test_search.py)."""
    import jax.numpy as jnp

    mirror.sync(idx)
    arr = mirror.arrays
    m = np.asarray(arr["alive"]).astype(bool)
    if pred.location is not None:
        m &= np.asarray(arr["location"] == pred.location) \
            if pred.location >= 0 else False
    if pred.exclude_hidden:
        m &= np.asarray(arr["hidden"] <= 0)
    if pred.kinds is not None:
        kinds = [k for k in pred.kinds if k >= 0]
        m &= np.asarray(jnp.isin(
            arr["kind"], jnp.asarray(kinds, dtype=jnp.int32))) \
            if kinds else False
    if pred.favorite is not None:
        m &= np.asarray(arr["favorite"] == pred.favorite) \
            if pred.favorite >= 0 else False
    if pred.size_lo is not None:
        m &= np.asarray((arr["size"] >= 0) & (arr["size"] >= pred.size_lo))
    if pred.size_hi is not None:
        m &= np.asarray((arr["size"] >= 0) & (arr["size"] <= pred.size_hi))
    if pred.exts is not None:
        ext_m = np.zeros(mirror.cap, dtype=bool)
        ext_len = np.asarray(arr["ext_len"])
        for needle in pred.exts:
            ext_m |= (kernels.exact_jnp(arr["ext"], needle, kernel)
                      & (ext_len == len(needle)))
        m &= ext_m
    if pred.path is not None:
        m &= (kernels.exact_jnp(arr["path"], pred.path, kernel)
              & (np.asarray(arr["path_len"]) == len(pred.path)))
    if pred.date_lo is not None or pred.date_hi is not None:
        valid = np.asarray(arr["date_len"]) >= 0
        if pred.date_lo is not None:
            m &= valid & (kernels.lex_cmp_jnp(arr["date"], pred.date_lo,
                                              kernel) >= 0)
        if pred.date_hi is not None:
            m &= valid & (kernels.lex_cmp_jnp(arr["date"], pred.date_hi,
                                              kernel) <= 0)
    if pred.needle is not None:
        m &= kernels.substring_jnp(arr["name"], pred.needle, kernel)
    m = m[: idx.n]
    _patch_overflow(idx, pred, m)
    return m


def _patch_overflow(idx: ColumnarIndex, pred: Predicate,
                    m: np.ndarray) -> None:
    """Re-decide every truncated row host-side against the full values —
    plane scoring may miss (a substring spanning the cut) or over-match
    (an exact prefix) there; the Python oracle is authoritative."""
    for row_id, fields in idx.overflow.items():
        slot = idx.slot_of(row_id)
        if slot is not None and slot < m.shape[0] and idx.alive[slot]:
            m[slot] = match_row(fields, pred)
