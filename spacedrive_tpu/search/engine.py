"""SearchEngine: the device-resident query engine (ISSUE 15 tentpole).

Turns the TPU from a scan-time tool into a serve-time one: ``search.paths``
and ``search.pathsCount`` queries are answered from a per-library
:class:`~.columnar.ColumnarIndex` scored by batched JAX/Pallas kernels
instead of a SQL LIKE table scan — the "GPUs as Storage System
Accelerators" framing (PAPERS.md, arxiv 1202.3669) applied to the query
tier, with SEDD's batched-scan discipline (arxiv 2501.01046) shaping the
kernels.

Correctness ladder (SQLite stays the oracle at every rung):

1. **Eligibility** — :func:`~.columnar.parse_predicate` accepts only
   filter sets the index answers bit-exactly; wildcards, tag subqueries
   and over-long needles stay on SQLite.
2. **Freshness** — the engine mirrors the PR 11 reader-pool watermark
   protocol: the same synchronous ``db.commit`` / ``invalidate_query``
   bus hooks bump a per-library ``pending`` counter, a refresh stamps the
   index with the watermark it read under, and a query is served from the
   index ONLY when the two are equal. A post-commit query can therefore
   never see pre-watermark rows — while a refresh is in flight the query
   falls back to SQLite.
3. **Scoring** — the per-query backend (device jnp/Pallas vs CPU numpy)
   is picked by the PR 6 :class:`~..objects.hasher.BackendRouter` (EWMA
   transfer-inclusive rates, hysteresis, periodic exploration) publishing
   ``sd_search_router_*``; a wedged device dispatch is deadline-bounded,
   degrades the route to CPU, and a CPU failure falls back to SQLite.
4. **Hydration** — the engine returns ROW IDS only; the router handler
   re-runs the exact SQL SELECT over ``fp.id IN (...)`` so ORDER BY /
   LIMIT / cursor semantics reproduce the SQL path byte-for-byte.

Refresh is **incremental**: appends ride an ``id > max_id`` scan
(AUTOINCREMENT ids are monotonic), updates/deletes ride the
:class:`~..models.base.RowJournal` change feed (model-helper writes note
their row; raw writes flood → full rebuild), and a COUNT(*) verify
catches anything that slipped past both (FK cascades into file_path).

``SD_SEARCH_ENGINE=device`` arms the engine (default ``sqlite`` keeps
every query on the SQL path); ``sd_search_*`` telemetry is catalogued in
docs/architecture/observability.md (drift-gated).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import TYPE_CHECKING, Any

import numpy as np

from .. import telemetry
from ..objects.hasher import BackendRouter, _bounded_call
from ..utils import env_float as _env_float
from ..utils import env_int as _env_int
from ..utils.locks import SdLock
from . import columnar
from .columnar import ColumnarIndex, DeviceMirror, Predicate, parse_predicate
from .kernels import resolve_kernel

if TYPE_CHECKING:
    from ..library import Library
    from ..node import Node

logger = logging.getLogger(__name__)

#: the reader-pool watermark bump set (server/pool.py BUMP_KINDS) — one
#: protocol, two consumers; conservative by design (over-bumping costs a
#: refresh, under-bumping would serve stale rows)
BUMP_KINDS = frozenset({"db.commit", "invalidate_query", "sync.newMessage",
                        "job_progress"})
#: DB-file swap (backup restore / repair): the whole index is void
RELOAD_KINDS = frozenset({"library.reload"})

#: procedures the engine can serve
ENGINE_PROCS = frozenset({"search.paths", "search.pathsCount"})

#: the per-row scan footprint the router's transfer-inclusive EWMA is fed
#: (plane widths + filter columns) — consistent across engines, which is
#: all a relative rate needs
ROW_BYTES = (columnar.W_NAME + columnar.W_PATH + columnar.W_EXT
             + columnar.W_DATE + 8 * 5 + 32)

# module handles — help text lives in telemetry._declare_core (the single
# copy); these are get-or-create lookups, the server/pool.py pattern
_INDEX_ROWS = telemetry.gauge("sd_search_index_rows", labels=("library",))
_INDEX_BYTES = telemetry.gauge("sd_search_index_bytes", labels=("library",))
_REFRESH_SECONDS = telemetry.histogram("sd_search_refresh_seconds")
_REFRESH_TOTAL = telemetry.counter("sd_search_refresh_total",
                                   labels=("kind",))
_REFRESH_LAG = telemetry.gauge("sd_search_refresh_lag", labels=("library",))
_QUERIES = telemetry.counter("sd_search_queries_total", labels=("backend",))
_QUERY_SECONDS = telemetry.histogram("sd_search_query_seconds",
                                     labels=("backend",))
_FALLBACKS = telemetry.counter("sd_search_fallbacks_total",
                               labels=("reason",))
_ROUTER_FLIPS = telemetry.counter("sd_search_router_flips_total")
_ROUTER_BATCHES = telemetry.counter("sd_search_router_batches_total",
                                    labels=("backend",))
_ROUTER_BPS = telemetry.gauge("sd_search_router_bytes_per_sec",
                              labels=("backend",))


class _LibState:
    """Per-library index + watermark state (all mutation under ``lock``)."""

    __slots__ = ("lib_id", "lock", "wm_lock", "refresh_lock", "index",
                 "mirror", "journal", "pending", "built_wm", "epoch",
                 "built_epoch")

    def __init__(self, lib_id: str, journal) -> None:
        self.lib_id = lib_id
        # one name for every instance: same-role per-library locks must
        # not register order edges against each other (utils/locks.py
        # skips same-name edges)
        self.lock = SdLock("search.engine.lib")
        # watermark fields get their own tiny lock so the SYNCHRONOUS
        # post-commit bump hook never waits behind a scoring pass or a
        # refresh holding ``lock`` — the committing thread must pay a
        # dict-update, not a 40 ms predicate scan. Nesting order where
        # both are held: lock → wm_lock.
        self.wm_lock = SdLock("search.engine.wm")
        # serializes whole refresh passes (refresher thread vs a
        # synchronous refresh_now): two interleaved passes could drain
        # the journal in one and stamp freshness from the other — an
        # empty incremental pass would then mark the index fresh while
        # the flood rebuild is still in flight
        self.refresh_lock = SdLock("search.engine.refresh")
        self.index: ColumnarIndex | None = None
        self.mirror = DeviceMirror()
        self.journal = journal
        self.pending = 0       # bumped by the bus hook, post-commit
        self.built_wm = -1     # pending value the index was built under
        self.epoch = 0         # bumped on library.reload (file swap)
        self.built_epoch = 0

    def fresh(self) -> bool:
        """Watermark equality under ``wm_lock`` only — safe to call with
        or without ``lock`` held (lock → wm_lock nesting order)."""
        with self.wm_lock:
            return (self.index is not None
                    and self.built_wm == self.pending
                    and self.built_epoch == self.epoch)


class SearchEngine:
    """One per Node (``node.search_engine``); None when the gate is off."""

    def __init__(self, node: "Node") -> None:
        self.node = node
        self.enabled = True
        self.max_hydrate = _env_int("SD_SEARCH_MAX_HYDRATE", 20_000)
        self.device_deadline_s = _env_float("SD_SEARCH_DEVICE_TIMEOUT_S",
                                            10.0)
        self._states: dict[str, _LibState] = {}
        self._states_lock = SdLock("search.engine.states")
        #: filter signatures whose candidate set exceeded max_hydrate —
        #: those dispatches should keep going to the reader pool instead
        #: of being pulled in-process only to score, overflow and run
        #: their (heaviest) SQL scan on the node. Bounded; insertion-
        #: order evicted. A predicate that later turns selective stays
        #: pooled — correct, merely without the device win.
        self._toolarge: dict[str, None] = {}
        self.router = BackendRouter(
            flips_counter=_ROUTER_FLIPS, batches_counter=_ROUTER_BATCHES,
            bps_gauge=_ROUTER_BPS, event_prefix="search_router")
        self._served = {"device": 0, "cpu": 0}
        self._wake = threading.Event()
        self._stopped = threading.Event()
        node.events.on(self._on_event)
        self._refresher_thread = threading.Thread(
            target=self._refresher, name="sd-search-refresher", daemon=True)
        self._refresher_thread.start()

    @classmethod
    def maybe_start(cls, node: "Node") -> "SearchEngine | None":
        """``SD_SEARCH_ENGINE=sqlite|device`` — default sqlite (the gate)."""
        gate = os.environ.get("SD_SEARCH_ENGINE", "sqlite").strip().lower()
        if gate != "device":
            return None
        return cls(node)

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        try:
            self.node.events.off(self._on_event)
        except Exception:
            pass
        self._refresher_thread.join(timeout=5)

    def set_enabled(self, value: bool) -> None:
        """Runtime bypass (the search bench's engine-vs-SQLite A/B):
        disabled, every lookup returns None and the handler serves SQL."""
        self.enabled = bool(value)

    # -- invalidation (the reader-pool protocol, second consumer) ------------
    def _on_event(self, event) -> None:
        lib_id = getattr(event, "library_id", None)
        if not lib_id:
            return
        state = self._states.get(lib_id)
        if state is None:
            return
        if event.kind in RELOAD_KINDS:
            with state.wm_lock:
                state.epoch += 1
                state.pending += 1
            self._wake.set()
        elif event.kind in BUMP_KINDS:
            with state.wm_lock:
                state.pending += 1
            self._wake.set()

    # -- registration --------------------------------------------------------
    def _ensure(self, library: "Library") -> _LibState:
        state = self._states.get(library.id)
        if state is not None:
            return state
        with self._states_lock:
            state = self._states.get(library.id)
            if state is None:
                journal = library.db.attach_row_journal(
                    ("file_path", "object"), flood_on_delete=("object",))
                state = _LibState(library.id, journal)
                self._states[library.id] = state
                self._wake.set()  # kick the initial build
        return state

    def ensure_library(self, library: "Library") -> None:
        self._ensure(library)

    # -- dispatch-time routing (api/router.resolve pool bypass) --------------
    def prefers_inprocess(self, key: str, library_id: str | None,
                          arg: Any) -> bool:
        """True when this dispatch should skip the reader pool because the
        in-process handler will serve it from the device index. Cheap:
        one dict lookup + predicate parse, no scoring."""
        if key not in ENGINE_PROCS or not self.enabled or not library_id:
            return False
        state = self._states.get(library_id)
        if state is None:
            # first sighting of this library: register it (builds in the
            # background) and let the pool serve meanwhile
            try:
                self._ensure(self.node.libraries.get(library_id))
            except Exception:
                pass
            return False
        if not state.fresh():
            return False
        pred, _why = parse_predicate(arg or {})
        if pred is None:
            return False
        if key == "search.paths":  # counts never hydrate — no size limit
            sig = self._filter_sig(library_id, arg)
            with self._states_lock:
                if sig in self._toolarge:
                    return False
        return True

    _FILTER_KEYS = ("location_id", "search", "extensions", "kinds",
                    "favorite", "include_hidden", "materialized_path",
                    "tags", "date_range", "size_range")

    @classmethod
    def _filter_sig(cls, lib_id: str | None, arg: Any) -> str:
        arg = arg if isinstance(arg, dict) else {}
        try:
            return f"{lib_id}|" + json.dumps(
                {k: arg.get(k) for k in cls._FILTER_KEYS},
                sort_keys=True, default=str)
        except (TypeError, ValueError):
            return f"{lib_id}|?"

    # -- the query surface ---------------------------------------------------
    def count(self, library: "Library", arg: Any) -> int | None:
        """search.pathsCount: the full answer (a mask sum), or None →
        serve SQL."""
        got = self._query(library, arg)
        if got is None:
            return None
        mask, _ids = got
        return int(mask.sum())

    def candidate_ids(self, library: "Library",
                      arg: Any) -> np.ndarray | None:
        """search.paths: the EXACT matching row-id set for the filter
        predicates (ordering/cursor/limit stay in SQL), or None → serve
        SQL. Candidate sets past ``SD_SEARCH_MAX_HYDRATE`` fall back —
        hydrating an unselective query through an IN-list would lose to
        the plain scan it replaces."""
        got = self._query(library, arg)
        if got is None:
            return None
        _mask, ids = got
        if ids is None or len(ids) > self.max_hydrate:
            _FALLBACKS.inc(reason="toolarge")
            with self._states_lock:
                self._toolarge[self._filter_sig(library.id, arg)] = None
                while len(self._toolarge) > 256:
                    self._toolarge.pop(next(iter(self._toolarge)))
            return None
        return ids

    def note_sqlite_serve(self, seconds: float) -> None:
        """The handler served via SQL while the engine is armed — keep the
        per-backend latency picture complete."""
        _QUERIES.inc(backend="sqlite")
        _QUERY_SECONDS.observe(seconds, backend="sqlite")

    def _query(self, library: "Library",
               arg: Any) -> tuple[np.ndarray, np.ndarray | None] | None:
        if not self.enabled:
            return None
        pred, why = parse_predicate(arg or {})
        if pred is None:
            _FALLBACKS.inc(reason=why or "ineligible")
            return None
        state = self._ensure(library)
        with state.lock:
            if not state.fresh():
                _FALLBACKS.inc(reason="stale")
                self._wake.set()
                return None
            t0 = time.perf_counter()
            main, probe = self.router.route()
            mask = self._score(state, pred, main)
            if mask is None and main == "device":
                # degraded mid-query: the CPU engine is the same index
                mask = self._score(state, pred, "cpu")
                main = "cpu"
            if mask is None:
                _FALLBACKS.inc(reason="error")
                return None
            dt = time.perf_counter() - t0
            n = state.index.n
            self.router.observe(main, n * ROW_BYTES, max(dt, 1e-9))
            _QUERIES.inc(backend=main)
            _QUERY_SECONDS.observe(dt, backend=main)
            with self._states_lock:  # int += is not atomic across threads
                self._served[main] += 1
            if probe is not None:
                # exploration: re-run this query on the losing engine so
                # its EWMA stays live (bounded to one query in EXPLORE_EVERY)
                t1 = time.perf_counter()
                if self._score(state, pred, probe) is not None:
                    self.router.observe(probe, n * ROW_BYTES,
                                        max(time.perf_counter() - t1, 1e-9))
            ids = state.index.ids[: state.index.n][mask]
        return mask, ids

    def _score(self, state: _LibState, pred: Predicate,
               backend: str) -> np.ndarray | None:
        """One scoring dispatch; a device failure/timeout degrades the
        route (bounded re-probe un-pins it later, the PR 6 discipline)."""
        idx = state.index
        if backend == "cpu":
            try:
                return columnar.eval_mask_cpu(idx, pred)
            except Exception:
                logger.exception("cpu search scoring failed")
                return None
        kernel = resolve_kernel()
        status, res = _bounded_call(
            lambda: columnar.eval_mask_device(idx, state.mirror, pred,
                                              kernel),
            self.device_deadline_s, "search-device-dispatch")
        if status == "ok":
            return res
        why = repr(res) if status == "error" else \
            "deadline exceeded (wedged device?)"
        logger.warning("device search scoring failed (%s); routing CPU", why)
        self.router.degrade(why)
        return None

    # -- refresh -------------------------------------------------------------
    def refresh_now(self, library: "Library") -> None:
        """Synchronous refresh to the current watermark (tests/bench)."""
        state = self._ensure(library)
        self._refresh_state(state)

    def _refresher(self) -> None:
        while not self._stopped.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stopped.is_set():
                return
            for state in list(self._states.values()):
                if state.fresh():
                    continue
                try:
                    self._refresh_state(state)
                except Exception:
                    # a failed refresh leaves the index stale — queries
                    # keep falling back to SQLite, the next bump retries
                    logger.exception("search index refresh failed for %s",
                                     state.lib_id)

    def _refresh_state(self, state: _LibState) -> None:
        """Bring the index up to the library's current watermark. SELECTs
        run OUTSIDE the state lock (the reader connection serves them);
        only the array mutation takes it. Loops until the watermark is
        stable across a whole pass."""
        with state.refresh_lock:
            self._refresh_state_locked(state)

    def _refresh_state_locked(self, state: _LibState) -> None:
        for _ in range(64):  # watermark churn bound; stale is always safe
            if self._stopped.is_set():
                return
            try:
                library = self.node.libraries.get(state.lib_id)
            except KeyError:
                return  # unloaded: queries 404 before reaching the index
            with state.lock:
                with state.wm_lock:
                    w0 = state.pending
                    e0 = state.epoch
                    built_epoch = state.built_epoch
                idx = state.index
                needs_full = idx is None or built_epoch != e0
                max_id = idx.max_id if idx is not None else 0
            t0 = time.perf_counter()
            drained = state.journal.drain()
            flood = bool(drained["flood"])
            if needs_full or flood:
                rows = library.db.query(
                    columnar.LOADER_SQL + " ORDER BY fp.id")
                with state.lock:
                    with state.wm_lock:
                        reloaded = state.epoch != e0
                    if reloaded:
                        continue  # reloaded mid-build: rebuild fresh
                    new_idx = ColumnarIndex()
                    new_idx.build(rows)
                    state.index = new_idx
                    with state.wm_lock:
                        state.built_epoch = e0
                        state.built_wm = w0
                        done = state.pending == w0
                    self._maybe_seed_router(state)
                _REFRESH_TOTAL.inc(kind="full")
            else:
                dirty = self._resolve_dirty(library, drained)
                if dirty is None:
                    # unresolvable note (vanished pub_id): full next pass
                    state.journal.publish_one("file_path", "flood", None)
                    continue
                fresh_rows = self._load_rows(library, dirty)
                appends = library.db.query(
                    columnar.LOADER_SQL + " WHERE fp.id > ? ORDER BY fp.id",
                    [max_id])
                total = library.db.query(
                    "SELECT COUNT(*) n FROM file_path")[0]["n"]
                with state.lock:
                    with state.wm_lock:
                        reloaded = state.epoch != e0
                    if reloaded or state.index is not idx:
                        continue
                    ok = True
                    found = set()
                    for row in fresh_rows:
                        found.add(int(row["id"]))
                        ok = ok and idx.upsert(row)
                    for row_id in dirty:
                        if row_id not in found:
                            idx.delete_id(row_id)
                    for row in appends:
                        ok = ok and idx.upsert(row)
                    ok = ok and idx.alive_count == total
                    if ok:
                        with state.wm_lock:
                            state.built_epoch = e0
                            state.built_wm = w0
                            done = state.pending == w0
                if not ok:
                    # out-of-order insert or an untracked cascade into
                    # file_path (e.g. a location CASCADE delete): rebuild
                    state.journal.publish_one("file_path", "flood", None)
                    continue
                _REFRESH_TOTAL.inc(kind="incremental")
            _REFRESH_SECONDS.observe(time.perf_counter() - t0)
            self._publish_gauges(state)
            if done:
                return

    def _maybe_seed_router(self, state: _LibState) -> None:
        """After the first full build (caller holds ``state.lock``): time
        one matches-nothing substring scan on BOTH engines so the router
        starts from measured rates instead of waiting an exploration
        cycle to discover the device (the fused-probe discipline the
        hash router is seeded with)."""
        if self.router.cpu_bps is not None or state.index is None \
                or state.index.n == 0:
            return
        probe = Predicate(needle=b"\x01\x01\x01")
        nbytes = state.index.n * ROW_BYTES
        for backend in ("cpu", "device"):
            t0 = time.perf_counter()
            if self._score(state, probe, backend) is not None:
                self.router.observe(backend, nbytes,
                                    max(time.perf_counter() - t0, 1e-9))

    def _resolve_dirty(self, library: "Library",
                       drained: dict[str, Any]) -> set[int] | None:
        """Journal notes → the file_path row-id set to re-select; None
        when a note cannot be resolved (forces a full rebuild)."""
        dirty: set[int] = set(drained["ids"].get("file_path", ()))
        fp_pubs = drained["pub_ids"].get("file_path", set())
        if fp_pubs:
            resolved = self._ids_for(
                library, "SELECT id FROM file_path WHERE pub_id IN ({})",
                sorted(fp_pubs))
            if len(resolved) < len(fp_pubs):
                return None  # a pub_id vanished: deletion we can't place
            dirty |= resolved
        obj_ids = drained["ids"].get("object", set())
        if obj_ids:
            dirty |= self._ids_for(
                library,
                "SELECT id FROM file_path WHERE object_id IN ({})",
                sorted(obj_ids))
        obj_pubs = drained["pub_ids"].get("object", set())
        if obj_pubs:
            dirty |= self._ids_for(
                library,
                "SELECT id FROM file_path WHERE object_id IN "
                "(SELECT id FROM object WHERE pub_id IN ({}))",
                sorted(obj_pubs))
        return dirty

    @staticmethod
    def _ids_for(library: "Library", sql_tpl: str,
                 values: list) -> set[int]:
        out: set[int] = set()
        for lo in range(0, len(values), 500):
            chunk = values[lo: lo + 500]
            marks = ",".join("?" for _ in chunk)
            for row in library.db.query(sql_tpl.format(marks), chunk):
                out.add(int(row["id"]))
        return out

    @staticmethod
    def _load_rows(library: "Library", ids: set[int]) -> list:
        rows: list = []
        ordered = sorted(ids)
        for lo in range(0, len(ordered), 500):
            chunk = ordered[lo: lo + 500]
            marks = ",".join("?" for _ in chunk)
            rows.extend(library.db.query(
                columnar.LOADER_SQL + f" WHERE fp.id IN ({marks})", chunk))
        return rows

    def _publish_gauges(self, state: _LibState) -> None:
        label = state.lib_id[:8]
        with state.lock:
            idx = state.index
            if idx is not None:
                _INDEX_ROWS.set(idx.alive_count, library=label)
                _INDEX_BYTES.set(idx.nbytes, library=label)
            with state.wm_lock:
                lag = max(0, state.pending - state.built_wm)
        _REFRESH_LAG.set(lag, library=label)

    # -- introspection -------------------------------------------------------
    def status(self) -> dict[str, Any]:
        libs = {}
        for lib_id, state in list(self._states.items()):
            with state.lock:
                idx = state.index
                with state.wm_lock:
                    pending, built_wm = state.pending, state.built_wm
                libs[lib_id] = {
                    "rows": idx.alive_count if idx is not None else 0,
                    "bytes": idx.nbytes if idx is not None else 0,
                    "overflow_rows": len(idx.overflow) if idx else 0,
                    "pending": pending,
                    "built_wm": built_wm,
                    "fresh": state.fresh(),
                }
        return {
            "enabled": self.enabled,
            "kernel": resolve_kernel(),
            "backend": self.router.current,
            "degraded": self.router.degraded,
            "served": dict(self._served),
            "libraries": libs,
        }
