"""Batched predicate scorers for the columnar search index.

The device engine scores substring / exact-match predicates over the
index's byte-plane layout — ``hay[(W, N)] u8`` where plane ``w`` holds
byte ``w`` of every row, the same lane discipline as the BLAKE3 Pallas
kernel (ops/blake3_pallas.py: lanes are independent work items, the last
axis is the VPU's native 128). Three implementations share one contract
and are byte-identical (tests/test_search.py):

- **numpy** (`*_np`) — the CPU engine and the oracle the others are
  proven against;
- **XLA** (`SD_SEARCH_KERNEL=xla`) — plain jnp ops, one fused compare
  tree per (needle length, plane count);
- **Pallas** (`SD_SEARCH_KERNEL=pallas`) — a hand-tiled kernel beside
  blake3_pallas: planes are tiled to (W, 32, 128) u8 blocks (32 sublanes
  is the int8-native tile), the needle rides SMEM, and the
  (W−L+1)-offset × L-byte compare tree is fully unrolled at trace time
  so the accumulator never leaves vector registers. On non-TPU backends
  it runs in Pallas interpret mode (pure-JAX evaluation) — byte-identical
  parity is provable on CPU while the device relay is down, exactly the
  blake3 discipline.

Semantics contract (what makes the engine's answers reproduce the SQL
path byte-for-byte):

- ``substring``: SQLite ``LIKE '%needle%'`` with both sides ASCII-folded
  (SQLite's default LIKE is case-insensitive for A-Z only); haystack
  planes are stored pre-folded, the needle is folded by the caller.
  Rows are zero-padded past their length, and needles never contain
  NUL, so padding can produce no false positive. Rows whose value was
  TRUNCATED at the plane width (len > W) may under-match here — the
  caller patches those few rows host-side (ColumnarIndex.overflow).
- ``exact``: SQL ``=`` (BINARY collation — byte equality). The needle is
  zero-padded to the plane width; equality of padded vectors ⟺ string
  equality whenever the stored value fit (len ≤ W). Truncated rows are
  again the caller's host-side patch.
"""

from __future__ import annotations

import functools
import os

import numpy as np

#: needles longer than this fall back to SQLite (the unrolled compare
#: tree stays bounded; search strings this long are vanishingly rare)
MAX_NEEDLE = 48

#: sublane rows per Pallas grid step — 32×128 is the int8-native tile
TILE_ROWS = 32
LANES = 128
TILE = TILE_ROWS * LANES


def fold(raw: bytes) -> bytes:
    """ASCII-fold (A-Z → a-z) — exactly SQLite's default LIKE folding;
    non-ASCII bytes compare exact there and here."""
    return raw.lower() if raw.isascii() else \
        bytes(b + 32 if 0x41 <= b <= 0x5A else b for b in raw)


def resolve_kernel() -> str:
    """``SD_SEARCH_KERNEL=pallas|xla`` per call (the blake3 discipline:
    jit caches are keyed per kernel, so flipping the env mid-process is
    safe). Default: pallas on a real TPU, xla elsewhere (interpret-mode
    pallas costs pure-JAX emulation overhead with no hardware payoff)."""
    raw = os.environ.get("SD_SEARCH_KERNEL", "").strip().lower()
    if raw in ("pallas", "xla"):
        return raw
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "xla"


# ---------------------------------------------------------------------------
# numpy — the CPU engine and the parity oracle
# ---------------------------------------------------------------------------


def substring_np(planes: np.ndarray, needle: bytes) -> np.ndarray:
    """``planes`` is (W, N) u8; returns an (N,) bool match mask."""
    w, n = planes.shape
    nlen = len(needle)
    if nlen == 0 or nlen > w:
        return np.zeros(n, dtype=bool)
    nb = np.frombuffer(needle, dtype=np.uint8)
    acc = np.zeros(n, dtype=bool)
    for j in range(w - nlen + 1):
        eq = planes[j] == nb[0]
        for k in range(1, nlen):
            if not eq.any():
                break
            eq = eq & (planes[j + k] == nb[k])
        acc |= eq
    return acc


def exact_np(planes: np.ndarray, needle: bytes) -> np.ndarray:
    """Byte equality of the zero-padded value vector (SQL ``=``)."""
    w, n = planes.shape
    if len(needle) > w:
        return np.zeros(n, dtype=bool)
    padded = np.zeros(w, dtype=np.uint8)
    padded[: len(needle)] = np.frombuffer(needle, dtype=np.uint8)
    eq = planes[0] == padded[0]
    for k in range(1, w):
        if not eq.any():
            break
        eq = eq & (planes[k] == padded[k])
    return eq


def presence_bitmap(planes: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """(N, 32) u8 byte-presence bitmap per row — the CPU engine's
    prescreen: a row can only contain a needle whose every byte is
    present in the row, so ``bitmap ⊇ needle-bytes`` prunes the exact
    window scan to a few percent of rows with zero false negatives.
    Byte value ``b`` lives at ``bits[:, b >> 3] & (1 << (b & 7))``
    (packbits bitorder='little'). Padding bytes (beyond ``lens``) are
    masked out so byte 0 means a literal NUL, not padding."""
    w, n = planes.shape
    bits = np.zeros((n, 32), dtype=np.uint8)
    chunk = 1 << 18  # bounds the (chunk, 256) one-hot temp to 64 MB
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        onehot = np.zeros((hi - lo, 256), dtype=bool)
        rows = np.arange(hi - lo)
        for j in range(w):
            live = j < lens[lo:hi]
            onehot[rows[live], planes[j, lo:hi][live]] = True
        bits[lo:hi] = np.packbits(onehot, axis=1, bitorder="little")
    return bits


def prescreen_np(bits: np.ndarray, needle: bytes) -> np.ndarray:
    """(N,) bool — rows whose presence bitmap covers every needle byte."""
    cand = np.ones(bits.shape[0], dtype=bool)
    for b in set(needle):
        cand &= (bits[:, b >> 3] & np.uint8(1 << (b & 7))) != 0
    return cand


def lex_cmp_np(planes: np.ndarray, bound: bytes) -> np.ndarray:
    """(N,) i8 memcmp verdict (-1 | 0 | 1) of each zero-padded row value
    against the zero-padded bound — exactly SQLite's BINARY collation on
    TEXT (a proper prefix is smaller, which zero padding preserves)."""
    w, n = planes.shape
    padded = np.zeros(w, dtype=np.uint8)
    padded[: min(len(bound), w)] = np.frombuffer(
        bound[:w], dtype=np.uint8)
    res = np.zeros(n, dtype=np.int8)
    for k in range(w):
        undecided = res == 0
        if not undecided.any():
            break
        d = planes[k][undecided].astype(np.int16) - np.int16(padded[k])
        res[undecided] = np.sign(d).astype(np.int8)
    return res


# ---------------------------------------------------------------------------
# XLA — plain jnp ops (one fused compare tree per static shape)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _substring_xla_jit(nlen: int, width: int):
    import jax
    import jax.numpy as jnp

    def run(planes, needle):  # (W, N) u8, (MAX_NEEDLE,) u8
        acc = jnp.zeros(planes.shape[1], dtype=jnp.bool_)
        for j in range(width - nlen + 1):
            eq = planes[j] == needle[0]
            for k in range(1, nlen):
                eq = eq & (planes[j + k] == needle[k])
            acc = acc | eq
        return acc

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _exact_xla_jit(width: int):
    import jax
    import jax.numpy as jnp

    def run(planes, padded):  # (W, N) u8, (W,) u8
        eq = planes[0] == padded[0]
        for k in range(1, width):
            eq = eq & (planes[k] == padded[k])
        return eq

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _lex_xla_jit(width: int):
    import jax
    import jax.numpy as jnp

    def run(planes, padded):  # (W, N) u8, (W,) u8 → (N,) i8 memcmp
        gt = jnp.zeros(planes.shape[1], dtype=jnp.bool_)
        lt = jnp.zeros(planes.shape[1], dtype=jnp.bool_)
        for k in range(width):
            undecided = ~gt & ~lt
            gt = gt | (undecided & (planes[k] > padded[k]))
            lt = lt | (undecided & (planes[k] < padded[k]))
        return gt.astype(jnp.int8) - lt.astype(jnp.int8)

    return jax.jit(run)


# ---------------------------------------------------------------------------
# Pallas — the hand-tiled kernel (interpret mode off-TPU)
# ---------------------------------------------------------------------------


def _interpret() -> bool:
    from ..ops.blake3_pallas import interpret_mode

    return interpret_mode()


@functools.lru_cache(maxsize=256)
def _substring_pallas_jit(nlen: int, width: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(needle_ref, hay_ref, out_ref):
        # hay_ref: (W, TILE_ROWS, LANES) u8; needle_ref: (1, MAX_NEEDLE)
        # i32 in SMEM. The offset × byte compare tree is unrolled at
        # trace time; acc lives in vector registers across all offsets.
        acc = jnp.zeros((TILE_ROWS, LANES), dtype=jnp.bool_)
        for j in range(width - nlen + 1):
            eq = hay_ref[j] == needle_ref[0, 0].astype(jnp.uint8)
            for k in range(1, nlen):
                eq = eq & (hay_ref[j + k]
                           == needle_ref[0, k].astype(jnp.uint8))
            acc = acc | eq
        out_ref[...] = acc.astype(jnp.uint8)

    def run(planes, needle):  # (W, R, 128) u8, (1, MAX_NEEDLE) i32
        rows = planes.shape[1]
        return pl.pallas_call(
            kernel,
            grid=(rows // TILE_ROWS,),
            in_specs=[
                pl.BlockSpec((1, MAX_NEEDLE), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((width, TILE_ROWS, LANES),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint8),
            interpret=interpret,
        )(needle, planes)

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _exact_pallas_jit(width: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(needle_ref, hay_ref, out_ref):
        eq = hay_ref[0] == needle_ref[0, 0].astype(jnp.uint8)
        for k in range(1, width):
            eq = eq & (hay_ref[k] == needle_ref[0, k].astype(jnp.uint8))
        out_ref[...] = eq.astype(jnp.uint8)

    def run(planes, padded):  # (W, R, 128) u8, (1, W) i32
        rows = planes.shape[1]
        return pl.pallas_call(
            kernel,
            grid=(rows // TILE_ROWS,),
            in_specs=[
                pl.BlockSpec((1, width), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((width, TILE_ROWS, LANES),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint8),
            interpret=interpret,
        )(padded, planes)

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _lex_pallas_jit(width: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(bound_ref, hay_ref, out_ref):
        # memcmp with tie propagation, unrolled over the plane axis;
        # encodes the verdict as u8 (0 = eq, 1 = gt, 2 = lt)
        gt = jnp.zeros((TILE_ROWS, LANES), dtype=jnp.bool_)
        lt = jnp.zeros((TILE_ROWS, LANES), dtype=jnp.bool_)
        for k in range(width):
            b = bound_ref[0, k].astype(jnp.uint8)
            undecided = ~gt & ~lt
            gt = gt | (undecided & (hay_ref[k] > b))
            lt = lt | (undecided & (hay_ref[k] < b))
        out_ref[...] = gt.astype(jnp.uint8) + 2 * lt.astype(jnp.uint8)

    def run(planes, bound):  # (W, R, 128) u8, (1, W) i32
        rows = planes.shape[1]
        return pl.pallas_call(
            kernel,
            grid=(rows // TILE_ROWS,),
            in_specs=[
                pl.BlockSpec((1, width), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((width, TILE_ROWS, LANES),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint8),
            interpret=interpret,
        )(bound, planes)

    return jax.jit(run)


# ---------------------------------------------------------------------------
# device entry points (the engine's device backend)
# ---------------------------------------------------------------------------
# ``planes`` here is a DEVICE-RESIDENT jnp array of shape (W, CAP) u8 with
# CAP a whole number of tiles (the DeviceMirror keeps it in sync with the
# columnar master incrementally — no per-query host→device transfer).
# Returns host numpy over the full CAP; callers slice [:n].


def pad_cap(n: int) -> int:
    """Device capacity for ``n`` rows: a whole number of Pallas tiles."""
    return max(TILE, -(-n // TILE) * TILE)


def substring_jnp(planes, needle: bytes, kernel: str) -> np.ndarray:
    """(CAP,) bool via the selected device kernel; byte-identical to
    :func:`substring_np` on the live rows (tests/test_search.py)."""
    import jax.numpy as jnp

    w, cap = planes.shape
    nlen = len(needle)
    if nlen == 0 or nlen > min(w, MAX_NEEDLE):
        return np.zeros(cap, dtype=bool)
    if kernel == "pallas":
        ndl = np.zeros((1, MAX_NEEDLE), dtype=np.int32)
        ndl[0, :nlen] = np.frombuffer(needle, dtype=np.uint8)
        out = _substring_pallas_jit(nlen, w, _interpret())(
            planes.reshape(w, cap // LANES, LANES), jnp.asarray(ndl))
        return np.asarray(out).reshape(-1).astype(bool)
    ndl = np.zeros(MAX_NEEDLE, dtype=np.uint8)
    ndl[:nlen] = np.frombuffer(needle, dtype=np.uint8)
    return np.asarray(_substring_xla_jit(nlen, w)(planes,
                                                  jnp.asarray(ndl)))


def exact_jnp(planes, needle: bytes, kernel: str) -> np.ndarray:
    import jax.numpy as jnp

    w, cap = planes.shape
    if len(needle) > w:
        return np.zeros(cap, dtype=bool)
    if kernel == "pallas":
        ndl = np.zeros((1, w), dtype=np.int32)
        ndl[0, : len(needle)] = np.frombuffer(needle, dtype=np.uint8)
        out = _exact_pallas_jit(w, _interpret())(
            planes.reshape(w, cap // LANES, LANES), jnp.asarray(ndl))
        return np.asarray(out).reshape(-1).astype(bool)
    padded = np.zeros(w, dtype=np.uint8)
    padded[: len(needle)] = np.frombuffer(needle, dtype=np.uint8)
    return np.asarray(_exact_xla_jit(w)(planes, jnp.asarray(padded)))


def lex_cmp_jnp(planes, bound: bytes, kernel: str) -> np.ndarray:
    """(CAP,) i8 memcmp verdict; parity with :func:`lex_cmp_np`."""
    import jax.numpy as jnp

    w, cap = planes.shape
    if kernel == "pallas":
        ndl = np.zeros((1, w), dtype=np.int32)
        clipped = bound[:w]
        ndl[0, : len(clipped)] = np.frombuffer(clipped, dtype=np.uint8)
        out = np.asarray(_lex_pallas_jit(w, _interpret())(
            planes.reshape(w, cap // LANES, LANES),
            jnp.asarray(ndl))).reshape(-1)
        res = np.zeros(cap, dtype=np.int8)
        res[out == 1] = 1
        res[out == 2] = -1
        return res
    padded = np.zeros(w, dtype=np.uint8)
    clipped = bound[:w]
    padded[: len(clipped)] = np.frombuffer(clipped, dtype=np.uint8)
    return np.asarray(_lex_xla_jit(w)(planes, jnp.asarray(padded)))
