"""Device-resident query engine (ISSUE 15): a columnar in-memory search
index over FilePath rows scored by batched JAX/Pallas kernels, refreshed
incrementally at the commit watermark, with SQLite as the oracle and the
fallback at every rung. See docs/architecture/serving.md ("Device query
engine") and docs/architecture/search.md."""

from .columnar import ColumnarIndex, Predicate, match_row, parse_predicate
from .engine import SearchEngine

__all__ = ["ColumnarIndex", "Predicate", "SearchEngine", "match_row",
           "parse_predicate"]
